"""ParagraphVectors / doc2vec (reference:
models/paragraphvectors/ParagraphVectors.java:47, sequence learning
impls models/embeddings/learning/impl/sequence/DBOW.java + DM.java).

Labels live in the same lookup table as words (reference behavior): each
label gets a vocab entry and a syn0 row. DBOW: the label row predicts each
word of its document through the word's HS path / negatives — exactly the
skipgram step with the label as the moving row. DM: the label is prepended
to every CBOW context window.

``infer_vector`` trains a fresh row against frozen syn1/syn1neg (reference:
ParagraphVectors.inferVector), as one jitted loop per iteration.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nlp.sequence_vectors import SequenceVectors
from deeplearning4j_tpu.nlp.vocab import Huffman, VocabWord


@partial(jax.jit, static_argnames=("use_hs", "use_ns"))
def _infer_step(vec, syn1, syn1neg, points, codes, code_mask, neg_targets,
                neg_labels, lr, *, use_hs: bool, use_ns: bool):
    """DBOW inference: move only ``vec`` [D]; syn1/syn1neg frozen."""
    grad = jnp.zeros_like(vec)
    if use_hs:
        w1 = syn1[points]  # [B, L, D]
        f = jax.nn.sigmoid(jnp.einsum("d,bld->bl", vec, w1))
        g = (1.0 - codes - f) * code_mask * lr
        grad = grad + jnp.einsum("bl,bld->d", g, w1)
    if use_ns:
        wn = syn1neg[neg_targets]
        f = jax.nn.sigmoid(jnp.einsum("d,bkd->bk", vec, wn))
        g = (neg_labels - f) * lr
        grad = grad + jnp.einsum("bk,bkd->d", g, wn)
    return vec + grad


class ParagraphVectors(SequenceVectors):
    """reference: ParagraphVectors.java:47 (builder + inferVector :~300)."""

    LABEL_PREFIX = "__label__"

    def __init__(self, sequence_algorithm: str = "dbow",
                 train_words: bool = False, **kw):
        kw.setdefault("elements_algorithm", "skipgram")
        # doc2vec batches must stay SMALL: every position of a document
        # carries the same label row, so a large batch sums hundreds of
        # stale-read label contributions into one step and distinct labels
        # collapse toward a common direction (measured: 2-label corpus,
        # batch 512 -> label cosine 0.99 and broken classification; batch
        # 64 -> cosine 0.19, correct). Words don't have this problem —
        # SequenceVectors keeps its large default.
        kw.setdefault("batch_size", 64)
        super().__init__(**kw)
        self.sequence_algorithm = sequence_algorithm.lower()
        self.train_words = train_words
        self._label_ids: dict = {}

    # --------------------------------------------------------------- native
    def _native_eligible_config(self) -> bool:
        """PV refinement of the SequenceVectors eligibility: the native
        kernels (native/skipgram.c pairs_train / cbow_train — the
        DBOW.java and DM.java hot loops) cover plain-NS DBOW and DM
        without word co-training; hierarchic softmax, subsampling, and
        train_words keep the device path. Composes with the shared gate
        so the common rule set lives in one place."""
        from deeplearning4j_tpu.native import (NATIVE_MAX_WINDOW,
                                               cbow_native_available,
                                               pairs_native_available)

        if not (self._native_common_eligible()
                and not self.train_words):
            return False
        if self.sequence_algorithm == "dbow":
            return pairs_native_available()
        return (self.sequence_algorithm == "dm"
                and 1 <= self.window <= NATIVE_MAX_WINDOW
                and cbow_native_available())

    def _fit_native_docs(self, entries) -> bool:
        """Train documents in the native kernels with the reference's
        sequential-accumulation semantics — DBOW as label->word NS pairs
        (DBOW.java), DM as CBOW windows with the label row appended to
        every context (DM.java) — tables host-side like Word2Vec's
        native path. Returns False when the native library is
        unavailable (caller uses the device path with the same
        entries)."""
        from deeplearning4j_tpu.native import cbow_train, ns_pairs_train

        syn0, syn1neg, table = self._native_tables()
        common = dict(negative=self.negative, alpha=self.learning_rate,
                      min_alpha=self.min_learning_rate,
                      epochs=self.epochs * self.iterations,
                      seed=self.seed or 1)
        if self.sequence_algorithm == "dbow":
            rows = np.concatenate(
                [np.full(idx.size, label_row, np.int32)
                 for idx, label_row in entries])
            targets = np.concatenate(
                [np.asarray(idx, np.int32) for idx, _ in entries])
            out = ns_pairs_train(syn0, syn1neg, rows, targets, table,
                                 **common)
        else:  # dm
            sep = np.asarray([-1], np.int32)
            corpus = np.concatenate(
                [np.concatenate([np.asarray(idx, np.int32), sep])
                 for idx, _ in entries])
            labels = np.concatenate(
                [np.concatenate([np.full(idx.size, label_row, np.int32),
                                 sep])
                 for idx, label_row in entries])
            out = cbow_train(syn0, syn1neg, corpus, table,
                             window=self.window, labels=labels, **common)
        if out is None:  # toolchain raced away: caller falls through to
            return False  # the device path with the same entries
        _, self.syn0, self.syn1neg = out
        return True

    # ------------------------------------------------------------------ vocab
    def _label_token(self, label: str) -> str:
        return self.LABEL_PREFIX + label

    def build_vocab_from_documents(self, documents) -> None:
        contents = [d.content for d in documents]
        self.build_vocab(contents)
        # add labels to the vocab (no huffman path needed for labels — they
        # are never predicted, only predictors), then rebuild indices+tree
        for d in documents:
            for label in d.labels:
                t = self._label_token(label)
                if not self.vocab.contains_word(t):
                    self.vocab.add_token(VocabWord(t, 1.0))
        self.vocab.update_indices()
        Huffman(self.vocab).build()

    # -------------------------------------------------------------------- fit
    def fit(self, documents) -> "ParagraphVectors":
        """Device-resident doc2vec: the host uploads a TOKEN stream plus a
        parallel LABEL stream (syn0 row id per position) and the whole
        epoch runs as one jitted scan per corpus block — the same
        transfer-minimal scheme as SequenceVectors._fit_element_epochs,
        replacing the round-3 one-dispatch-per-document loop (measured
        ~10-100x slower from dispatch and host pair assembly alone).

        Label syn0 updates run with dup_cap=inf: one label row appears in
        every pair/window of its document, so the duplicate cap would
        attenuate label training ~batch/cap-fold; uncapped summation is
        the full-batch gradient for that row against near-frozen word
        targets (reference: sequential accumulation in DBOW.java/DM.java).
        Multi-label documents repeat their tokens once per label, matching
        the reference's per-label iteration."""
        from deeplearning4j_tpu.nlp.learning import (DUP_CAP,
                                                     cbow_corpus_epoch,
                                                     dbow_corpus_epoch,
                                                     skipgram_corpus_epoch)

        if self.sequence_algorithm not in ("dbow", "dm"):
            raise ValueError(
                f"Unknown sequence algorithm '{self.sequence_algorithm}'")
        documents = list(documents)
        if self.vocab is None:
            self.build_vocab_from_documents(documents)
        if self.syn0 is None:
            self.reset_weights()
        self._label_ids = {
            label: self.vocab.index_of(self._label_token(label))
            for d in documents for label in d.labels}
        b = self._builder
        entries, total_tokens = [], 0
        for d in documents:
            tokens = self.tokenizer_factory.create(d.content).tokens()
            idx = b.lookup_indices(tokens)
            if idx.size == 0:
                continue
            for label in d.labels:
                entries.append((idx, self._label_ids[label]))
                total_tokens += idx.size
        if not entries:
            return self
        if self._use_native_backend() and self._fit_native_docs(entries):
            return self
        B, W, K = self.batch_size, self.window, self.negative
        if self.use_hs:
            points_tab = jnp.asarray(b.points)
            codes_tab = jnp.asarray(b.codes)
            cmask_tab = jnp.asarray(b.code_mask)
        else:
            points_tab = jnp.zeros((1, 1), jnp.int32)
            codes_tab = jnp.zeros((1, 1), jnp.float32)
            cmask_tab = jnp.zeros((1, 1), jnp.float32)
        neg_table = (jnp.asarray(b._neg_table) if K > 0
                     else jnp.zeros((1,), jnp.int32))
        total_units = max(total_tokens * self.epochs * self.iterations, 1)
        done = 0
        # without subsampling every pass trains on identical streams —
        # assemble and upload them once, not once per epoch x iteration
        static_streams = None if self.sampling > 0 else \
            self._doc_streams(entries, B, W)
        static_words = None
        if self.sampling <= 0 and self.train_words:
            static_words = self._token_stream(
                [idx for idx, _ in entries], B, W)
        for e in range(self.epochs):
            for it in range(self.iterations):
                if self.sampling > 0:
                    ent = [(b.subsample(idx), lab) for idx, lab in entries]
                    toks, labs = self._doc_streams(ent, B, W)
                    words_stream = (self._token_stream(
                        [idx for idx, _ in ent], B, W)
                        if self.train_words else None)
                else:
                    toks, labs = static_streams
                    words_stream = static_words
                lr0 = self._alpha(min(done / total_units, 1.0))
                lr1 = self._alpha(min((done + total_tokens) / total_units,
                                      1.0))
                key = jax.random.fold_in(
                    jax.random.PRNGKey(self.seed + 7),
                    done + e * 131071 + it)
                done += total_tokens
                if toks is None:
                    continue
                inf = jnp.float32(np.inf)
                if self.sequence_algorithm == "dbow":
                    self.syn0, self.syn1, self.syn1neg = dbow_corpus_epoch(
                        self.syn0, self.syn1, self.syn1neg, toks, labs,
                        key, jnp.float32(lr0), jnp.float32(lr1),
                        jnp.float32(DUP_CAP), inf, points_tab, codes_tab,
                        cmask_tab, neg_table, batch=B, neg_k=max(K, 0),
                        use_hs=self.use_hs, use_ns=K > 0)
                else:
                    self.syn0, self.syn1, self.syn1neg = cbow_corpus_epoch(
                        self.syn0, self.syn1, self.syn1neg, toks, labs,
                        key, jnp.float32(lr0), jnp.float32(lr1),
                        jnp.float32(DUP_CAP), inf, points_tab, codes_tab,
                        cmask_tab, neg_table, window=W, batch=B,
                        neg_k=max(K, 0), use_hs=self.use_hs, use_ns=K > 0,
                        with_labels=True)
                if self.train_words and words_stream is not None:
                    # trainWords=true: ordinary skipgram over the same
                    # corpus (reference: ParagraphVectors trainWords flag)
                    self.syn0, self.syn1, self.syn1neg = \
                        skipgram_corpus_epoch(
                            self.syn0, self.syn1, self.syn1neg,
                            words_stream, jax.random.fold_in(key, 1),
                            jnp.float32(lr0), jnp.float32(lr1),
                            jnp.float32(DUP_CAP),
                            points_tab, codes_tab, cmask_tab, neg_table,
                            window=W, batch=B, neg_k=max(K, 0),
                            use_hs=self.use_hs, use_ns=K > 0)
        return self

    @classmethod
    def _doc_streams(cls, entries, batch: int, window: int):
        """Parallel (token, label-row) streams with -1 separators, padded
        to the 'positions' bucket (N % batch == 0)."""
        tparts, lparts = [], []
        for idx, lab in entries:
            if idx.size:
                tparts.append(idx.astype(np.int32))
                tparts.append(np.full(1, -1, np.int32))
                lparts.append(np.full(idx.size, lab, np.int32))
                lparts.append(np.full(1, -1, np.int32))
        if not tparts:
            return None, None
        t = np.concatenate(tparts)
        lab = np.concatenate(lparts)
        n = cls._bucket_size(t.size, batch, window, "positions")
        pad = np.full(n - t.size, -1, np.int32)
        return (jnp.asarray(np.concatenate([t, pad])),
                jnp.asarray(np.concatenate([lab, pad])))

    # ------------------------------------------------------------- inference
    def infer_vector(self, text: str, learning_rate: float = 0.01,
                     iterations: int = 5, seed: int = 0) -> np.ndarray:
        """Train a fresh paragraph vector for unseen text (reference:
        ParagraphVectors.inferVector)."""
        tokens = self.tokenizer_factory.create(text).tokens()
        idx = self._builder.sentence_to_indices(tokens)
        rng = np.random.RandomState(seed)
        vec = jnp.asarray(
            (rng.random_sample(self.layer_size) - 0.5) / self.layer_size,
            jnp.float32)
        if idx.size == 0:
            return np.asarray(vec)
        b = self._builder
        points, codes, mask = b.hs_arrays(idx)
        neg_rng = np.random.RandomState(seed + 1)
        for _ in range(iterations):
            negs = b.sample_negatives(idx, rng=neg_rng)
            vec = _infer_step(vec, self.syn1, self.syn1neg,
                              jnp.asarray(points), jnp.asarray(codes),
                              jnp.asarray(mask), jnp.asarray(negs),
                              jnp.asarray(b.neg_labels(idx.size)),
                              jnp.float32(learning_rate),
                              use_hs=self.use_hs, use_ns=self.negative > 0)
        return np.asarray(vec)

    # ------------------------------------------------------------- query API
    def labels(self) -> list:
        return list(self._label_ids)

    def label_vector(self, label: str) -> np.ndarray:
        return np.asarray(self.syn0[self._label_ids[label]])

    def similarity_to_label(self, text: str, label: str) -> float:
        v = self.infer_vector(text)
        lv = self.label_vector(label)
        denom = max(np.linalg.norm(v) * np.linalg.norm(lv), 1e-12)
        return float(np.dot(v, lv) / denom)

    def predict(self, text: str) -> str:
        """Nearest label for unseen text (reference:
        ParagraphVectors.predict)."""
        v = self.infer_vector(text)
        best, best_sim = None, -2.0
        for label in self._label_ids:
            lv = self.label_vector(label)
            denom = max(np.linalg.norm(v) * np.linalg.norm(lv), 1e-12)
            sim = float(np.dot(v, lv) / denom)
            if sim > best_sim:
                best, best_sim = label, sim
        return best
