"""NLP embedding stack (reference: deeplearning4j-nlp-parent).

- ``tokenization`` — tokenizer factories, sentence iterators, preprocessors
  (reference: text/tokenization/, text/sentenceiterator/)
- ``vocab`` — VocabWord, VocabCache, VocabConstructor, Huffman tree
  (reference: models/word2vec/wordstore/, models/word2vec/Huffman.java)
- ``learning`` — SkipGram/CBOW updates as single jitted scatter programs
  (reference: models/embeddings/learning/impl/elements/)
- ``sequence_vectors`` — the generic embedding trainer engine
  (reference: models/sequencevectors/SequenceVectors.java)
- ``word2vec`` / ``paragraph_vectors`` / ``glove`` — model facades
  (reference: models/word2vec/, models/paragraphvectors/, models/glove/)
- ``serde`` — word-vector serialization incl. Google word2vec binary format
  (reference: models/embeddings/loader/WordVectorSerializer.java)
- ``bagofwords`` — BoW / TF-IDF vectorizers (reference: bagofwords/)
"""

from deeplearning4j_tpu.nlp.vocab import (
    AbstractCache,
    Huffman,
    VocabConstructor,
    VocabWord,
)
from deeplearning4j_tpu.nlp.tokenization import (
    CollectionSentenceIterator,
    CommonPreprocessor,
    CjkTokenizerFactory,
    DefaultTokenizerFactory,
    FileSentenceIterator,
    LineSentenceIterator,
)
from deeplearning4j_tpu.nlp.sequence_vectors import SequenceVectors
from deeplearning4j_tpu.nlp.word2vec import Word2Vec
from deeplearning4j_tpu.nlp.paragraph_vectors import ParagraphVectors
from deeplearning4j_tpu.nlp.glove import Glove

__all__ = [
    "AbstractCache", "Huffman", "VocabConstructor", "VocabWord",
    "CollectionSentenceIterator", "CommonPreprocessor",
    "CjkTokenizerFactory", "DefaultTokenizerFactory", "FileSentenceIterator", "LineSentenceIterator",
    "SequenceVectors", "Word2Vec", "ParagraphVectors", "Glove",
]
