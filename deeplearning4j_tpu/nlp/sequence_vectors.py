"""SequenceVectors: the generic embedding trainer engine.

Reference: models/sequencevectors/SequenceVectors.java:187-216 (fit: build
vocab -> reset weights -> spawn VectorCalculationsThreads), :336-356
(trainSequence dispatch to elements/sequence learning algorithms).

TPU-native redesign: instead of worker threads racing on shared syn0/syn1
(the reference's Hogwild-style update), sentences are tokenized on host,
minibatches of (center, context) pairs are assembled by ``BatchBuilder``, and
each batch is ONE jitted scatter step (nlp/learning.py). Linear LR decay
matches the reference (alpha * (1 - progress), floored at min_learning_rate).

Word relationship queries (similarity, words_nearest) ride on the normalised
syn0 matrix — one [V, D] @ [D] matmul on device.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nlp.learning import (
    DUP_CAP,
    BatchBuilder,
    cbow_step,
    skipgram_epoch,
    skipgram_step,
)
from deeplearning4j_tpu.nlp.tokenization import DefaultTokenizerFactory
from deeplearning4j_tpu.nlp.vocab import AbstractCache, VocabConstructor


class SequenceVectors:
    """Configurable embedding trainer (reference builder fields map to
    keyword arguments of the same meaning)."""

    def __init__(self, layer_size: int = 100, window: int = 5,
                 min_word_frequency: int = 1, epochs: int = 1,
                 iterations: int = 1, learning_rate: float = 0.025,
                 min_learning_rate: float = 1e-4, negative: int = 0,
                 use_hierarchic_softmax: bool = True, sampling: float = 0.0,
                 batch_size: int = 512, seed: int = 12345,
                 elements_algorithm: str = "skipgram",
                 tokenizer_factory=None):
        self.layer_size = layer_size
        self.window = window
        self.min_word_frequency = min_word_frequency
        self.epochs = epochs
        self.iterations = iterations
        self.learning_rate = learning_rate
        self.min_learning_rate = min_learning_rate
        self.negative = negative
        self.use_hs = use_hierarchic_softmax
        if not use_hierarchic_softmax and negative <= 0:
            raise ValueError("Need hierarchical softmax and/or negative>0")
        self.sampling = sampling
        self.batch_size = batch_size
        self.seed = seed
        self.elements_algorithm = elements_algorithm.lower()
        self.tokenizer_factory = tokenizer_factory or \
            DefaultTokenizerFactory()
        self.vocab: Optional[AbstractCache] = None
        self.syn0 = None
        self.syn1 = None
        self.syn1neg = None
        self._builder: Optional[BatchBuilder] = None

    # ------------------------------------------------------------------ vocab
    def build_vocab(self, sentences) -> None:
        self.vocab = VocabConstructor(
            min_word_frequency=self.min_word_frequency,
            tokenizer_factory=self.tokenizer_factory,
            build_huffman=True).build_vocab(sentences)

    def reset_weights(self) -> None:
        """syn0 ~ U(-0.5/D, 0.5/D), syn1/syn1neg zeros (reference:
        InMemoryLookupTable.resetWeights)."""
        V, D = self.vocab.num_words(), self.layer_size
        rng = np.random.RandomState(self.seed)
        self.syn0 = jnp.asarray(
            (rng.random_sample((V, D)) - 0.5) / D, jnp.float32)
        self.syn1 = jnp.zeros((V, D), jnp.float32)
        self.syn1neg = jnp.zeros((V, D), jnp.float32)
        self._builder = BatchBuilder(
            self.vocab, window=self.window, negative=self.negative,
            use_hs=self.use_hs, sampling=self.sampling, seed=self.seed)

    # -------------------------------------------------------------------- fit
    def fit(self, sentences) -> "SequenceVectors":
        """Build vocab (if absent) and train (reference: fit :187-216).

        Pairs from MANY sentences accumulate into one fixed-size device batch
        before each jitted step — the dispatch-granularity change that makes
        this fast on TPU (the reference instead runs many threads of tiny
        native ops; here one scatter step carries ~batch_size pairs, so the
        host->device round-trip amortises and XLA sees constant shapes)."""
        if self.vocab is None:
            self.build_vocab(sentences)
        if self.syn0 is None:
            self.reset_weights()
        if self.elements_algorithm == "skipgram":
            return self._fit_skipgram_epochs(sentences)
        if self.elements_algorithm != "cbow":
            raise ValueError("Unknown elements algorithm "
                             f"'{self.elements_algorithm}'")
        total_words = max(self.vocab.total_word_count, 1.0)
        total_expected = total_words * self.epochs * self.iterations
        seen = 0.0
        for _ in range(self.epochs):
            if hasattr(sentences, "reset"):
                sentences.reset()
            for sentence in sentences:
                tokens = self.tokenizer_factory.create(sentence).tokens() \
                    if isinstance(sentence, str) else list(sentence)
                idx = self._builder.sentence_to_indices(tokens)
                for _ in range(self.iterations):
                    self._cbow_sentence(
                        idx, self._alpha(seen / total_expected))
                seen += idx.size
        return self

    def _fit_skipgram_epochs(self, sentences) -> "SequenceVectors":
        """Device-resident skipgram training: tokenize once, generate every
        (center, context) pair of an epoch in one vectorised host pass
        (``BatchBuilder.pairs_from_corpus``), pad to [S, batch_size], and run
        ONE jitted ``lax.scan`` per epoch (``skipgram_epoch``). Epochs share
        a padded batch count so the program compiles once.

        Pair order is shuffled within an epoch (the per-offset vectorised
        generation already abandons strict corpus order; a permutation
        decorrelates batches). LR decays linearly over batches to
        min_learning_rate, matching the reference's words-seen decay."""
        b = self._builder
        if hasattr(sentences, "reset"):
            sentences.reset()
        # Tokenize + vocab-index once (no subsampling yet); group sentences
        # into blocks of ~BLOCK_TOKENS so pair arrays are generated
        # streaming per block, not for the whole corpus at once — host
        # memory stays O(block), a 100M-token corpus never materialises
        # tens of GB of pairs.
        BLOCK_TOKENS = 1 << 21
        blocks, cur, cur_tokens, total_tokens = [], [], 0, 0
        for sentence in sentences:
            tokens = self.tokenizer_factory.create(sentence).tokens() \
                if isinstance(sentence, str) else list(sentence)
            idx = b.lookup_indices(tokens)
            if idx.size == 0:
                continue
            cur.append(idx)
            cur_tokens += idx.size
            total_tokens += idx.size
            if cur_tokens >= BLOCK_TOKENS:
                blocks.append(cur)
                cur, cur_tokens = [], 0
        if cur:
            blocks.append(cur)
        B = self.batch_size
        chunk = 128  # max scan batches per dispatch (bounds staging memory)
        done, n_total = 0, 0
        for e in range(self.epochs):
            for bi, block in enumerate(blocks):
                # fresh subsampling draw and dynamic windows per epoch
                # (reference resamples both every pass over the corpus)
                cs, xs = [], []
                for _ in range(self.iterations):
                    # fresh subsampling draw and dynamic windows per
                    # iteration and epoch (reference resamples both on
                    # every pass over the corpus)
                    sent_idx = [b.subsample(sid) for sid in block] \
                        if self.sampling > 0 else block
                    ci, xi = b.pairs_from_corpus(sent_idx)
                    cs.append(ci)
                    xs.append(xi)
                centers = np.concatenate(cs)
                contexts = np.concatenate(xs)
                if not centers.size:
                    continue
                perm = b.rng.permutation(centers.size)
                centers, contexts = centers[perm], contexts[perm]
                if n_total == 0:
                    # LR-schedule denominator, set at the first non-empty
                    # block: pairs per RAW token (subsampling ratio folds
                    # in automatically) extrapolated over the corpus;
                    # progress is clamped to 1 in _skipgram_dispatch
                    per_tok = centers.size / max(
                        sum(sid.size for sid in block), 1)
                    n_total = max(int(per_tok * total_tokens) * self.epochs,
                                  1)
                off = 0
                while off < centers.size:
                    take = min(chunk * B, centers.size - off)
                    done = self._skipgram_dispatch(
                        centers[off:off + take], contexts[off:off + take],
                        done, n_total)
                    off += take
        return self

    def _skipgram_dispatch(self, centers, contexts, done, n_total) -> int:
        """Stage one chunk of pairs as [S, B] device arrays and run the
        jitted epoch scan. S is padded to a power of two so at most
        log2(chunk)+1 program shapes ever compile."""
        b, B = self._builder, self.batch_size
        P, L, K = centers.size, b.max_code_len, self.negative
        S = 1
        while S * B < P:
            S *= 2
        pad = S * B - P
        # predicted word = center (its huffman path / NS positive); the syn0
        # row that moves = context (reference SkipGram iterateSample
        # (currentWord=center, lastWord=context) updates syn0[lastWord])
        rows = np.concatenate([contexts, np.zeros(pad, np.int32)])
        pred = np.concatenate([centers, np.zeros(pad, np.int32)])
        mask = np.concatenate([np.ones(P, np.float32),
                               np.zeros(pad, np.float32)])
        if self.use_hs:
            points = b.points[pred].reshape(S, B, L)
            codes = b.codes[pred].reshape(S, B, L)
            cmask = b.code_mask[pred].reshape(S, B, L)
        else:  # dummy single-level arrays keep the jit signature static
            points = np.zeros((S, B, 1), np.int32)
            codes = np.zeros((S, B, 1), np.float32)
            cmask = np.zeros((S, B, 1), np.float32)
        if K > 0:
            negs = b.sample_negatives(pred).reshape(S, B, 1 + K)
            nlab = np.zeros((S, B, 1 + K), np.float32)
            nlab[..., 0] = 1.0
        else:
            negs = np.zeros((S, B, 1), np.int32)
            nlab = np.zeros((S, B, 1), np.float32)
        # linear LR decay by global pair progress (reference: alpha by words
        # seen), floored at min_learning_rate
        prog = np.minimum((done + np.arange(S) * B) / n_total, 1.0)
        lrs = np.maximum(self.min_learning_rate,
                         self.learning_rate * (1.0 - prog)).astype(np.float32)
        self.syn0, self.syn1, self.syn1neg = skipgram_epoch(
            self.syn0, self.syn1, self.syn1neg,
            jnp.asarray(rows.reshape(S, B)),
            jnp.asarray(points), jnp.asarray(codes), jnp.asarray(cmask),
            jnp.asarray(negs), jnp.asarray(nlab),
            jnp.asarray(mask.reshape(S, B)), jnp.asarray(lrs),
            jnp.float32(DUP_CAP), use_hs=self.use_hs, use_ns=K > 0)
        return done + P

    def _alpha(self, progress: float) -> float:
        return max(self.min_learning_rate,
                   self.learning_rate * (1.0 - progress))

    def _skipgram_batch(self, rows: np.ndarray, predicted: np.ndarray,
                        lr: float, dup_cap: float = DUP_CAP) -> None:
        """rows: syn0 rows to move (context words); predicted: words whose
        huffman path / positive NS target is used (reference
        SkipGram.iterateSample(currentWord=predicted, lastWord=row)).
        dup_cap=inf restores pure summation (doc2vec label training)."""
        b = self._builder
        points, codes, mask = b.hs_arrays(predicted)
        negs = b.sample_negatives(predicted)
        self.syn0, self.syn1, self.syn1neg = skipgram_step(
            self.syn0, self.syn1, self.syn1neg, jnp.asarray(rows),
            jnp.asarray(points), jnp.asarray(codes), jnp.asarray(mask),
            jnp.asarray(negs), jnp.asarray(b.neg_labels(rows.size)),
            jnp.float32(lr), jnp.float32(dup_cap),
            use_hs=self.use_hs, use_ns=self.negative > 0)

    def _cbow_sentence(self, idx: np.ndarray, lr: float,
                       extra_context: Optional[np.ndarray] = None,
                       dup_cap: float = DUP_CAP) -> None:
        """Assemble [B, C] context windows per center word, one jitted step.
        ``extra_context`` (e.g. a paragraph label id per sequence) is
        prepended to every window (the DM trick)."""
        b = self._builder
        if idx.size < 2:
            return
        C = 2 * self.window + (1 if extra_context is not None else 0)
        B = idx.size
        ctx = np.zeros((B, C), np.int32)
        cmask = np.zeros((B, C), np.float32)
        bs = b.rng.randint(0, self.window, size=B)
        for i in range(B):
            k = 0
            if extra_context is not None:
                ctx[i, k] = extra_context[i]
                cmask[i, k] = 1.0
                k += 1
            win = self.window - bs[i]
            for j in range(max(0, i - win), min(B, i + win + 1)):
                if j != i and k < C:
                    ctx[i, k] = idx[j]
                    cmask[i, k] = 1.0
                    k += 1
        points, codes, mask = b.hs_arrays(idx)
        negs = b.sample_negatives(idx)
        self.syn0, self.syn1, self.syn1neg = cbow_step(
            self.syn0, self.syn1, self.syn1neg, jnp.asarray(ctx),
            jnp.asarray(cmask), jnp.asarray(points), jnp.asarray(codes),
            jnp.asarray(mask), jnp.asarray(negs),
            jnp.asarray(b.neg_labels(B)), jnp.float32(lr),
            jnp.float32(dup_cap), use_hs=self.use_hs,
            use_ns=self.negative > 0)

    # ------------------------------------------------------------ query API
    def word_vector(self, word: str) -> Optional[np.ndarray]:
        i = self.vocab.index_of(word)
        return None if i < 0 else np.asarray(self.syn0[i])

    def has_word(self, word: str) -> bool:
        return self.vocab is not None and self.vocab.contains_word(word)

    def _norm_syn0(self) -> np.ndarray:
        s = np.asarray(self.syn0)
        n = np.linalg.norm(s, axis=1, keepdims=True)
        return s / np.maximum(n, 1e-12)

    def similarity(self, a: str, b: str) -> float:
        """Cosine similarity (reference: WordVectorsImpl.similarity)."""
        ia, ib = self.vocab.index_of(a), self.vocab.index_of(b)
        if ia < 0 or ib < 0:
            return float("nan")
        s = self._norm_syn0()
        return float(np.dot(s[ia], s[ib]))

    def words_nearest(self, word_or_vec, top_n: int = 10) -> list:
        """Top-N cosine neighbours (reference: wordsNearest)."""
        if isinstance(word_or_vec, str):
            i = self.vocab.index_of(word_or_vec)
            if i < 0:
                return []
            vec = np.asarray(self.syn0[i])
            exclude = {i}
        else:
            vec = np.asarray(word_or_vec)
            exclude = set()
        s = self._norm_syn0()
        v = vec / max(np.linalg.norm(vec), 1e-12)
        sims = s @ v
        order = np.argsort(-sims)
        out = []
        for j in order:
            if int(j) in exclude:
                continue
            out.append((self.vocab.word_at_index(int(j)), float(sims[j])))
            if len(out) >= top_n:
                break
        return out

    def words_nearest_sum(self, positive: list, negative: list,
                          top_n: int = 10) -> list:
        """king - man + woman style analogy (reference: wordsNearestSum)."""
        s = self._norm_syn0()
        vec = np.zeros(self.layer_size, np.float64)
        exclude = set()
        for w in positive:
            i = self.vocab.index_of(w)
            if i >= 0:
                vec += s[i]
                exclude.add(i)
        for w in negative:
            i = self.vocab.index_of(w)
            if i >= 0:
                vec -= s[i]
                exclude.add(i)
        v = vec / max(np.linalg.norm(vec), 1e-12)
        sims = s @ v
        order = np.argsort(-sims)
        out = []
        for j in order:
            if int(j) in exclude:
                continue
            out.append((self.vocab.word_at_index(int(j)), float(sims[j])))
            if len(out) >= top_n:
                break
        return out
