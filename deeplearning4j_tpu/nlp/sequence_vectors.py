"""SequenceVectors: the generic embedding trainer engine.

Reference: models/sequencevectors/SequenceVectors.java:187-216 (fit: build
vocab -> reset weights -> spawn VectorCalculationsThreads), :336-356
(trainSequence dispatch to elements/sequence learning algorithms).

TPU-native redesign: instead of worker threads racing on shared syn0/syn1
(the reference's Hogwild-style update), sentences are tokenized on host,
minibatches of (center, context) pairs are assembled by ``BatchBuilder``, and
each batch is ONE jitted scatter step (nlp/learning.py). Linear LR decay
matches the reference (alpha * (1 - progress), floored at min_learning_rate).

Word relationship queries (similarity, words_nearest) ride on the normalised
syn0 matrix — one [V, D] @ [D] matmul on device.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nlp.learning import (
    DUP_CAP,
    BatchBuilder,
    cbow_corpus_epoch,
    skipgram_corpus_epoch,
)
from deeplearning4j_tpu.nlp.tokenization import DefaultTokenizerFactory
from deeplearning4j_tpu.nlp.vocab import AbstractCache, VocabConstructor


class SequenceVectors:
    """Configurable embedding trainer (reference builder fields map to
    keyword arguments of the same meaning)."""

    def __init__(self, layer_size: int = 100, window: int = 5,
                 min_word_frequency: int = 1, epochs: int = 1,
                 iterations: int = 1, learning_rate: float = 0.025,
                 min_learning_rate: float = 1e-4, negative: int = 0,
                 use_hierarchic_softmax: bool = True, sampling: float = 0.0,
                 batch_size: int = 512, seed: int = 12345,
                 elements_algorithm: str = "skipgram",
                 tokenizer_factory=None, backend: str = "auto"):
        if backend not in ("auto", "device", "native"):
            raise ValueError(f"Unknown backend '{backend}'")
        self.layer_size = layer_size
        self.window = window
        self.min_word_frequency = min_word_frequency
        self.epochs = epochs
        self.iterations = iterations
        self.learning_rate = learning_rate
        self.min_learning_rate = min_learning_rate
        self.negative = negative
        self.use_hs = use_hierarchic_softmax
        if not use_hierarchic_softmax and negative <= 0:
            raise ValueError("Need hierarchical softmax and/or negative>0")
        self.sampling = sampling
        self.batch_size = batch_size
        self.seed = seed
        self.elements_algorithm = elements_algorithm.lower()
        self.tokenizer_factory = tokenizer_factory or \
            DefaultTokenizerFactory()
        self.backend = backend
        self.vocab: Optional[AbstractCache] = None
        self.syn0 = None
        self.syn1 = None
        self.syn1neg = None
        self._builder: Optional[BatchBuilder] = None

    # ------------------------------------------------------------------ vocab
    def build_vocab(self, sentences) -> None:
        self.vocab = VocabConstructor(
            min_word_frequency=self.min_word_frequency,
            tokenizer_factory=self.tokenizer_factory,
            build_huffman=True).build_vocab(sentences)

    def reset_weights(self) -> None:
        """syn0 ~ U(-0.5/D, 0.5/D), syn1/syn1neg zeros (reference:
        InMemoryLookupTable.resetWeights).

        Tables start HOST-side when the native backend will train (a
        device round-trip of the full tables through the TPU tunnel
        measured ~40% of native-path fit time); jnp consumers (queries,
        the device path, shard_embedding_tables) convert on demand."""
        V, D = self.vocab.num_words(), self.layer_size
        rng = np.random.RandomState(self.seed)
        syn0 = ((rng.random_sample((V, D)) - 0.5) / D).astype(np.float32)
        if self._native_eligible_config():
            self.syn0 = syn0
            self.syn1 = np.zeros((V, D), np.float32)
            self.syn1neg = np.zeros((V, D), np.float32)
        else:
            self.syn0 = jnp.asarray(syn0)
            self.syn1 = jnp.zeros((V, D), jnp.float32)
            self.syn1neg = jnp.zeros((V, D), jnp.float32)
        self._builder = BatchBuilder(
            self.vocab, window=self.window, negative=self.negative,
            use_hs=self.use_hs, sampling=self.sampling, seed=self.seed)

    # -------------------------------------------------------------------- fit
    def fit(self, sentences) -> "SequenceVectors":
        """Build vocab (if absent) and train (reference: fit :187-216).

        Pairs from MANY sentences accumulate into one fixed-size device batch
        before each jitted step — the dispatch-granularity change that makes
        this fast on TPU (the reference instead runs many threads of tiny
        native ops; here one scatter step carries ~batch_size pairs, so the
        host->device round-trip amortises and XLA sees constant shapes)."""
        if self.vocab is None:
            self.build_vocab(sentences)
        if self.syn0 is None:
            self.reset_weights()
        if self.elements_algorithm not in ("skipgram", "cbow"):
            raise ValueError("Unknown elements algorithm "
                             f"'{self.elements_algorithm}'")
        if self._use_native_backend():
            return self._fit_native(sentences)
        return self._fit_element_epochs(sentences)

    def _use_native_backend(self) -> bool:
        """Route eligible configs to the native C hot loop — the
        reference's own architecture (SkipGram.java's hot op is a native
        libnd4j kernel, not JVM code): plain negative-sampling skip-gram
        is a scatter-bound workload a CPU inner loop beats the device
        scatter path at (measured 210k vs 184k words/s on the bench
        config, profiles/w2v_baseline.py); CBOW has its own native
        kernel. The device path keeps hierarchic softmax, subsampling,
        and SHARDED embedding tables (nlp/distributed.py EP training),
        which the host loops cannot see."""
        from deeplearning4j_tpu.native import skipgram_native_available

        if self.backend == "device":
            return False
        sh = getattr(self.syn0, "sharding", None)
        unsharded = sh is None or len(sh.device_set) <= 1
        eligible = self._native_eligible_config() and unsharded
        if self.backend == "native":
            if not eligible:
                raise ValueError(
                    "backend='native' requires a config the native "
                    "kernels support — negative-sampling skip-gram/CBOW "
                    "(Word2Vec) or DBOW / DM without train_words "
                    "(ParagraphVectors) on unsharded tables; no HS, no "
                    "subsampling — and the C toolchain")
            return True
        return eligible

    def _native_common_eligible(self) -> bool:
        """Conditions shared by every native kernel (subclass eligibility
        composes with this — one place for the rule set). layer_size is
        part of it: the C accumulator is a fixed NATIVE_MAX_LAYER buffer
        and a runtime rejection would otherwise silently fall back AFTER
        consuming a possibly non-restartable sentence stream."""
        from deeplearning4j_tpu.native import (NATIVE_MAX_LAYER,
                                               skipgram_native_available)

        return (self.backend != "device"
                and not self.use_hs and self.negative > 0
                and self.sampling == 0.0
                and self.layer_size <= NATIVE_MAX_LAYER
                and skipgram_native_available())

    def _native_eligible_config(self) -> bool:
        """Config-level (pre-array) native-backend eligibility. The
        per-kernel availability probes guard against a stale .so missing
        the newer symbols — a runtime rejection would otherwise fall back
        AFTER consuming a possibly non-restartable sentence stream."""
        from deeplearning4j_tpu.native import (NATIVE_MAX_WINDOW,
                                               cbow_native_available)

        if not (self._native_common_eligible() and self.window >= 1):
            return False
        if self.elements_algorithm == "skipgram":
            return True
        return (self.elements_algorithm == "cbow"
                and self.window <= NATIVE_MAX_WINDOW
                and cbow_native_available())

    def _native_tables(self):
        """(syn0, syn1neg, unigram^0.75 table) as host arrays for the C
        kernels. Host tables train in place; a device-resident table is
        pulled once (and stays host-side after — queries convert on
        demand). One implementation for every native consumer."""
        counts = self.vocab.counts_array()
        p = counts ** 0.75
        p /= p.sum()
        table = np.repeat(np.arange(len(p), dtype=np.int32),
                          np.maximum(1, (p * 1_000_000).astype(np.int64)))
        syn0 = np.ascontiguousarray(np.asarray(self.syn0), np.float32)
        syn1neg = np.ascontiguousarray(np.asarray(self.syn1neg),
                                       np.float32)
        return syn0, syn1neg, table

    def _fit_native(self, sentences) -> "SequenceVectors":
        """Train via native/skipgram.c in place of the jitted epoch
        (skip-gram or CBOW — the AggregateSkipGram / CBOW.java loops)."""
        from deeplearning4j_tpu.native import cbow_train, skipgram_train

        if hasattr(sentences, "reset"):
            sentences.reset()
        cache = self.vocab
        corpus = []
        for sentence in sentences:
            tokens = self.tokenizer_factory.create(sentence).tokens() \
                if isinstance(sentence, str) else list(sentence)
            any_tok = False
            for tok in tokens:
                i = cache.index_of(tok)
                if i >= 0:
                    corpus.append(i)
                    any_tok = True
            if any_tok:
                corpus.append(-1)
        if not corpus:
            return self
        syn0, syn1neg, table = self._native_tables()
        kernel = (skipgram_train if self.elements_algorithm == "skipgram"
                  else cbow_train)
        out = kernel(
            syn0, syn1neg, np.asarray(corpus, np.int32), table,
            window=self.window, negative=self.negative,
            alpha=self.learning_rate, min_alpha=self.min_learning_rate,
            epochs=self.epochs * self.iterations, seed=self.seed or 1)
        if out is None:  # toolchain raced away: device fallback
            # ``sentences`` may be a one-shot generator the corpus walk
            # above already consumed — re-iterating it would train on
            # NOTHING. Rebuild token sentences from the materialized
            # index corpus instead (vocab words only, which is exactly
            # the token stream the device path trains on anyway).
            rebuilt, cur = [], []
            for i in corpus:
                if i < 0:
                    rebuilt.append(cur)
                    cur = []
                else:
                    cur.append(cache.word_at_index(i))
            if cur:
                rebuilt.append(cur)
            return self._fit_element_epochs(rebuilt)
        _, self.syn0, self.syn1neg = out
        return self

    def _fit_element_epochs(self, sentences) -> "SequenceVectors":
        """Device-resident skipgram/CBOW training, transfer-minimal: the host
        uploads only the TOKEN STREAM (4 bytes/token, -1 sentence
        separators); pair generation, negative sampling, huffman-path
        gathers, and the whole batched update scan run inside ONE jitted
        program per corpus block (``skipgram_corpus_epoch``). Rationale:
        staging pre-built pair batches costs ~25 bytes/pair over the
        host->device link and was the measured round-3 bottleneck.

        Blocks of ~BLOCK_TOKENS bound device/host memory; token streams are
        padded to power-of-two buckets so compile count stays logarithmic.
        LR decays linearly over the whole run to min_learning_rate
        (reference: words-seen decay)."""
        b = self._builder
        if hasattr(sentences, "reset"):
            sentences.reset()
        BLOCK_TOKENS = 1 << 21
        blocks, cur, cur_tokens, total_tokens = [], [], 0, 0
        for sentence in sentences:
            tokens = self.tokenizer_factory.create(sentence).tokens() \
                if isinstance(sentence, str) else list(sentence)
            idx = b.lookup_indices(tokens)
            if idx.size == 0:
                continue
            cur.append(idx)
            cur_tokens += idx.size
            total_tokens += idx.size
            if cur_tokens >= BLOCK_TOKENS:
                blocks.append(cur)
                cur, cur_tokens = [], 0
        if cur:
            blocks.append(cur)
        if not blocks:
            return self
        B, W, K = self.batch_size, self.window, self.negative
        L = b.max_code_len
        # device-resident lookup tables, uploaded once per fit
        if self.use_hs:
            points_tab = jnp.asarray(b.points)
            codes_tab = jnp.asarray(b.codes)
            cmask_tab = jnp.asarray(b.code_mask)
        else:
            points_tab = jnp.zeros((1, 1), jnp.int32)
            codes_tab = jnp.zeros((1, 1), jnp.float32)
            cmask_tab = jnp.zeros((1, 1), jnp.float32)
        neg_table = (jnp.asarray(b._neg_table) if K > 0
                     else jnp.zeros((1,), jnp.int32))
        total_units = max(total_tokens * self.epochs * self.iterations, 1)
        done = 0
        for e in range(self.epochs):
            for block in blocks:
                for it in range(self.iterations):
                    # fresh subsampling draw per pass (reference resamples
                    # every epoch/iteration); dynamic windows are drawn on
                    # device from the per-call rng key
                    sent_idx = [b.subsample(sid) for sid in block] \
                        if self.sampling > 0 else block
                    mode = ("pairs" if self.elements_algorithm == "skipgram"
                            else "positions")
                    stream = self._token_stream(sent_idx, B, W, mode=mode)
                    if stream is None:
                        continue
                    raw = sum(sid.size for sid in block)
                    lr0 = self._alpha(min(done / total_units, 1.0))
                    lr1 = self._alpha(min((done + raw) / total_units, 1.0))
                    key = jax.random.fold_in(
                        jax.random.PRNGKey(self.seed + 1),
                        done + e * 131071 + it)
                    if self.elements_algorithm == "skipgram":
                        self.syn0, self.syn1, self.syn1neg = \
                            skipgram_corpus_epoch(
                                self.syn0, self.syn1, self.syn1neg,
                                stream, key, jnp.float32(lr0),
                                jnp.float32(lr1), jnp.float32(DUP_CAP),
                                points_tab, codes_tab, cmask_tab, neg_table,
                                window=W, batch=B, neg_k=max(K, 0),
                                use_hs=self.use_hs, use_ns=K > 0)
                    else:
                        self.syn0, self.syn1, self.syn1neg = \
                            cbow_corpus_epoch(
                                self.syn0, self.syn1, self.syn1neg,
                                stream, stream, key, jnp.float32(lr0),
                                jnp.float32(lr1), jnp.float32(DUP_CAP),
                                jnp.float32(DUP_CAP),
                                points_tab, codes_tab, cmask_tab, neg_table,
                                window=W, batch=B, neg_k=max(K, 0),
                                use_hs=self.use_hs, use_ns=K > 0,
                                with_labels=False)
                    done += raw
        return self

    # Above this size, stream shapes snap to multiples of it instead of
    # powers of two: pow2 rounding wastes up to 50% of the scan on -1
    # padding for large corpora (a 2.1M-token block would pad to 4.2M),
    # while quantum rounding caps waste at Q/size (<7%) and still bounds
    # the number of compiled shapes.
    _STREAM_QUANTUM = 1 << 17

    @classmethod
    def _bucket_size(cls, size: int, batch: int, window: int,
                     mode: str) -> int:
        """Bucketed stream length N: powers of two below _STREAM_QUANTUM
        (small corpora, tests), multiples of it above (large corpora) —
        logarithmic-then-linear shape count, bounded padding waste either
        way. mode 'pairs' (skipgram: N*2W pairs reshape to batches) needs
        N*2W % batch == 0; 'positions' (CBOW/DBOW: one unit per position)
        needs N % batch == 0."""
        def ok(n):
            return ((n * 2 * window) % batch == 0 if mode == "pairs"
                    else n % batch == 0)

        q = cls._STREAM_QUANTUM
        if size <= q:
            n = max(int(batch), 2)
            while n < size or not ok(n):
                n *= 2
        else:
            n = ((size + q - 1) // q) * q
            while not ok(n):
                n += q
        return n

    @classmethod
    def _token_stream(cls, sent_idx, batch: int, window: int,
                      mode: str = "pairs"):
        """Concatenate sentences with -1 separators, pad with -1 to the
        bucketed length (see _bucket_size)."""
        parts = []
        for sid in sent_idx:
            if sid.size:
                parts.append(sid.astype(np.int32))
                parts.append(np.full(1, -1, np.int32))
        if not parts:
            return None
        stream = np.concatenate(parts)
        n = cls._bucket_size(stream.size, batch, window, mode)
        return jnp.asarray(np.concatenate(
            [stream, np.full(n - stream.size, -1, np.int32)]))

    def _alpha(self, progress: float) -> float:
        return max(self.min_learning_rate,
                   self.learning_rate * (1.0 - progress))

    # ------------------------------------------------------------ query API
    def word_vector(self, word: str) -> Optional[np.ndarray]:
        i = self.vocab.index_of(word)
        return None if i < 0 else np.asarray(self.syn0[i])

    def has_word(self, word: str) -> bool:
        return self.vocab is not None and self.vocab.contains_word(word)

    def _norm_syn0(self) -> np.ndarray:
        # slice off any mesh-padding rows (nlp/distributed.py pads tables
        # to a multiple of the model-axis size) so zero pad rows can never
        # rank in nearest-neighbour queries
        s = np.asarray(self.syn0)[:self.vocab.num_words()]
        n = np.linalg.norm(s, axis=1, keepdims=True)
        return s / np.maximum(n, 1e-12)

    def similarity(self, a: str, b: str) -> float:
        """Cosine similarity (reference: WordVectorsImpl.similarity)."""
        ia, ib = self.vocab.index_of(a), self.vocab.index_of(b)
        if ia < 0 or ib < 0:
            return float("nan")
        s = self._norm_syn0()
        return float(np.dot(s[ia], s[ib]))

    def words_nearest(self, word_or_vec, top_n: int = 10) -> list:
        """Top-N cosine neighbours (reference: wordsNearest)."""
        if isinstance(word_or_vec, str):
            i = self.vocab.index_of(word_or_vec)
            if i < 0:
                return []
            vec = np.asarray(self.syn0[i])
            exclude = {i}
        else:
            vec = np.asarray(word_or_vec)
            exclude = set()
        s = self._norm_syn0()
        v = vec / max(np.linalg.norm(vec), 1e-12)
        sims = s @ v
        order = np.argsort(-sims)
        out = []
        for j in order:
            if int(j) in exclude:
                continue
            out.append((self.vocab.word_at_index(int(j)), float(sims[j])))
            if len(out) >= top_n:
                break
        return out

    def words_nearest_sum(self, positive: list, negative: list,
                          top_n: int = 10) -> list:
        """king - man + woman style analogy (reference: wordsNearestSum)."""
        s = self._norm_syn0()
        vec = np.zeros(self.layer_size, np.float64)
        exclude = set()
        for w in positive:
            i = self.vocab.index_of(w)
            if i >= 0:
                vec += s[i]
                exclude.add(i)
        for w in negative:
            i = self.vocab.index_of(w)
            if i >= 0:
                vec -= s[i]
                exclude.add(i)
        v = vec / max(np.linalg.norm(vec), 1e-12)
        sims = s @ v
        order = np.argsort(-sims)
        out = []
        for j in order:
            if int(j) in exclude:
                continue
            out.append((self.vocab.word_at_index(int(j)), float(sims[j])))
            if len(out) >= top_n:
                break
        return out
