"""SequenceVectors: the generic embedding trainer engine.

Reference: models/sequencevectors/SequenceVectors.java:187-216 (fit: build
vocab -> reset weights -> spawn VectorCalculationsThreads), :336-356
(trainSequence dispatch to elements/sequence learning algorithms).

TPU-native redesign: instead of worker threads racing on shared syn0/syn1
(the reference's Hogwild-style update), sentences are tokenized on host,
minibatches of (center, context) pairs are assembled by ``BatchBuilder``, and
each batch is ONE jitted scatter step (nlp/learning.py). Linear LR decay
matches the reference (alpha * (1 - progress), floored at min_learning_rate).

Word relationship queries (similarity, words_nearest) ride on the normalised
syn0 matrix — one [V, D] @ [D] matmul on device.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nlp.learning import (
    DUP_CAP,
    BatchBuilder,
    cbow_step,
    skipgram_corpus_epoch,
    skipgram_step,
)
from deeplearning4j_tpu.nlp.tokenization import DefaultTokenizerFactory
from deeplearning4j_tpu.nlp.vocab import AbstractCache, VocabConstructor


class SequenceVectors:
    """Configurable embedding trainer (reference builder fields map to
    keyword arguments of the same meaning)."""

    def __init__(self, layer_size: int = 100, window: int = 5,
                 min_word_frequency: int = 1, epochs: int = 1,
                 iterations: int = 1, learning_rate: float = 0.025,
                 min_learning_rate: float = 1e-4, negative: int = 0,
                 use_hierarchic_softmax: bool = True, sampling: float = 0.0,
                 batch_size: int = 512, seed: int = 12345,
                 elements_algorithm: str = "skipgram",
                 tokenizer_factory=None):
        self.layer_size = layer_size
        self.window = window
        self.min_word_frequency = min_word_frequency
        self.epochs = epochs
        self.iterations = iterations
        self.learning_rate = learning_rate
        self.min_learning_rate = min_learning_rate
        self.negative = negative
        self.use_hs = use_hierarchic_softmax
        if not use_hierarchic_softmax and negative <= 0:
            raise ValueError("Need hierarchical softmax and/or negative>0")
        self.sampling = sampling
        self.batch_size = batch_size
        self.seed = seed
        self.elements_algorithm = elements_algorithm.lower()
        self.tokenizer_factory = tokenizer_factory or \
            DefaultTokenizerFactory()
        self.vocab: Optional[AbstractCache] = None
        self.syn0 = None
        self.syn1 = None
        self.syn1neg = None
        self._builder: Optional[BatchBuilder] = None

    # ------------------------------------------------------------------ vocab
    def build_vocab(self, sentences) -> None:
        self.vocab = VocabConstructor(
            min_word_frequency=self.min_word_frequency,
            tokenizer_factory=self.tokenizer_factory,
            build_huffman=True).build_vocab(sentences)

    def reset_weights(self) -> None:
        """syn0 ~ U(-0.5/D, 0.5/D), syn1/syn1neg zeros (reference:
        InMemoryLookupTable.resetWeights)."""
        V, D = self.vocab.num_words(), self.layer_size
        rng = np.random.RandomState(self.seed)
        self.syn0 = jnp.asarray(
            (rng.random_sample((V, D)) - 0.5) / D, jnp.float32)
        self.syn1 = jnp.zeros((V, D), jnp.float32)
        self.syn1neg = jnp.zeros((V, D), jnp.float32)
        self._builder = BatchBuilder(
            self.vocab, window=self.window, negative=self.negative,
            use_hs=self.use_hs, sampling=self.sampling, seed=self.seed)

    # -------------------------------------------------------------------- fit
    def fit(self, sentences) -> "SequenceVectors":
        """Build vocab (if absent) and train (reference: fit :187-216).

        Pairs from MANY sentences accumulate into one fixed-size device batch
        before each jitted step — the dispatch-granularity change that makes
        this fast on TPU (the reference instead runs many threads of tiny
        native ops; here one scatter step carries ~batch_size pairs, so the
        host->device round-trip amortises and XLA sees constant shapes)."""
        if self.vocab is None:
            self.build_vocab(sentences)
        if self.syn0 is None:
            self.reset_weights()
        if self.elements_algorithm == "skipgram":
            return self._fit_skipgram_epochs(sentences)
        if self.elements_algorithm != "cbow":
            raise ValueError("Unknown elements algorithm "
                             f"'{self.elements_algorithm}'")
        total_words = max(self.vocab.total_word_count, 1.0)
        total_expected = total_words * self.epochs * self.iterations
        seen = 0.0
        for _ in range(self.epochs):
            if hasattr(sentences, "reset"):
                sentences.reset()
            for sentence in sentences:
                tokens = self.tokenizer_factory.create(sentence).tokens() \
                    if isinstance(sentence, str) else list(sentence)
                idx = self._builder.sentence_to_indices(tokens)
                for _ in range(self.iterations):
                    self._cbow_sentence(
                        idx, self._alpha(seen / total_expected))
                seen += idx.size
        return self

    def _fit_skipgram_epochs(self, sentences) -> "SequenceVectors":
        """Device-resident skipgram training, transfer-minimal: the host
        uploads only the TOKEN STREAM (4 bytes/token, -1 sentence
        separators); pair generation, negative sampling, huffman-path
        gathers, and the whole batched update scan run inside ONE jitted
        program per corpus block (``skipgram_corpus_epoch``). Rationale:
        staging pre-built pair batches costs ~25 bytes/pair over the
        host->device link and was the measured round-3 bottleneck.

        Blocks of ~BLOCK_TOKENS bound device/host memory; token streams are
        padded to power-of-two buckets so compile count stays logarithmic.
        LR decays linearly over the whole run to min_learning_rate
        (reference: words-seen decay)."""
        b = self._builder
        if hasattr(sentences, "reset"):
            sentences.reset()
        BLOCK_TOKENS = 1 << 21
        blocks, cur, cur_tokens, total_tokens = [], [], 0, 0
        for sentence in sentences:
            tokens = self.tokenizer_factory.create(sentence).tokens() \
                if isinstance(sentence, str) else list(sentence)
            idx = b.lookup_indices(tokens)
            if idx.size == 0:
                continue
            cur.append(idx)
            cur_tokens += idx.size
            total_tokens += idx.size
            if cur_tokens >= BLOCK_TOKENS:
                blocks.append(cur)
                cur, cur_tokens = [], 0
        if cur:
            blocks.append(cur)
        if not blocks:
            return self
        B, W, K = self.batch_size, self.window, self.negative
        L = b.max_code_len
        # device-resident lookup tables, uploaded once per fit
        if self.use_hs:
            points_tab = jnp.asarray(b.points)
            codes_tab = jnp.asarray(b.codes)
            cmask_tab = jnp.asarray(b.code_mask)
        else:
            points_tab = jnp.zeros((1, 1), jnp.int32)
            codes_tab = jnp.zeros((1, 1), jnp.float32)
            cmask_tab = jnp.zeros((1, 1), jnp.float32)
        neg_table = (jnp.asarray(b._neg_table) if K > 0
                     else jnp.zeros((1,), jnp.int32))
        total_units = max(total_tokens * self.epochs * self.iterations, 1)
        done = 0
        for e in range(self.epochs):
            for block in blocks:
                for it in range(self.iterations):
                    # fresh subsampling draw per pass (reference resamples
                    # every epoch/iteration); dynamic windows are drawn on
                    # device from the per-call rng key
                    sent_idx = [b.subsample(sid) for sid in block] \
                        if self.sampling > 0 else block
                    stream = self._token_stream(sent_idx, B, W)
                    if stream is None:
                        continue
                    raw = sum(sid.size for sid in block)
                    lr0 = self._alpha(min(done / total_units, 1.0))
                    lr1 = self._alpha(min((done + raw) / total_units, 1.0))
                    key = jax.random.fold_in(
                        jax.random.PRNGKey(self.seed + 1),
                        done + e * 131071 + it)
                    self.syn0, self.syn1, self.syn1neg = \
                        skipgram_corpus_epoch(
                            self.syn0, self.syn1, self.syn1neg,
                            stream, key, jnp.float32(lr0),
                            jnp.float32(lr1), jnp.float32(DUP_CAP),
                            points_tab, codes_tab, cmask_tab, neg_table,
                            window=W, batch=B, neg_k=max(K, 0),
                            use_hs=self.use_hs, use_ns=K > 0)
                    done += raw
        return self

    @staticmethod
    def _token_stream(sent_idx, batch: int, window: int):
        """Concatenate sentences with -1 separators, pad with -1 to the
        smallest power-of-two N >= batch with N*2W % batch == 0 (bounds the
        number of compiled program shapes)."""
        parts = []
        for sid in sent_idx:
            if sid.size:
                parts.append(sid.astype(np.int32))
                parts.append(np.full(1, -1, np.int32))
        if not parts:
            return None
        stream = np.concatenate(parts)
        n = max(int(batch), 2)
        while n < stream.size or (n * 2 * window) % batch:
            n *= 2
        return jnp.asarray(np.concatenate(
            [stream, np.full(n - stream.size, -1, np.int32)]))

    def _alpha(self, progress: float) -> float:
        return max(self.min_learning_rate,
                   self.learning_rate * (1.0 - progress))

    def _skipgram_batch(self, rows: np.ndarray, predicted: np.ndarray,
                        lr: float, dup_cap: float = DUP_CAP) -> None:
        """rows: syn0 rows to move (context words); predicted: words whose
        huffman path / positive NS target is used (reference
        SkipGram.iterateSample(currentWord=predicted, lastWord=row)).
        dup_cap=inf restores pure summation (doc2vec label training)."""
        b = self._builder
        points, codes, mask = b.hs_arrays(predicted)
        negs = b.sample_negatives(predicted)
        self.syn0, self.syn1, self.syn1neg = skipgram_step(
            self.syn0, self.syn1, self.syn1neg, jnp.asarray(rows),
            jnp.asarray(points), jnp.asarray(codes), jnp.asarray(mask),
            jnp.asarray(negs), jnp.asarray(b.neg_labels(rows.size)),
            jnp.float32(lr), jnp.float32(dup_cap),
            use_hs=self.use_hs, use_ns=self.negative > 0)

    def _cbow_sentence(self, idx: np.ndarray, lr: float,
                       extra_context: Optional[np.ndarray] = None,
                       dup_cap: float = DUP_CAP) -> None:
        """Assemble [B, C] context windows per center word, one jitted step.
        ``extra_context`` (e.g. a paragraph label id per sequence) is
        prepended to every window (the DM trick)."""
        b = self._builder
        if idx.size < 2:
            return
        C = 2 * self.window + (1 if extra_context is not None else 0)
        B = idx.size
        ctx = np.zeros((B, C), np.int32)
        cmask = np.zeros((B, C), np.float32)
        bs = b.rng.randint(0, self.window, size=B)
        for i in range(B):
            k = 0
            if extra_context is not None:
                ctx[i, k] = extra_context[i]
                cmask[i, k] = 1.0
                k += 1
            win = self.window - bs[i]
            for j in range(max(0, i - win), min(B, i + win + 1)):
                if j != i and k < C:
                    ctx[i, k] = idx[j]
                    cmask[i, k] = 1.0
                    k += 1
        points, codes, mask = b.hs_arrays(idx)
        negs = b.sample_negatives(idx)
        self.syn0, self.syn1, self.syn1neg = cbow_step(
            self.syn0, self.syn1, self.syn1neg, jnp.asarray(ctx),
            jnp.asarray(cmask), jnp.asarray(points), jnp.asarray(codes),
            jnp.asarray(mask), jnp.asarray(negs),
            jnp.asarray(b.neg_labels(B)), jnp.float32(lr),
            jnp.float32(dup_cap), use_hs=self.use_hs,
            use_ns=self.negative > 0)

    # ------------------------------------------------------------ query API
    def word_vector(self, word: str) -> Optional[np.ndarray]:
        i = self.vocab.index_of(word)
        return None if i < 0 else np.asarray(self.syn0[i])

    def has_word(self, word: str) -> bool:
        return self.vocab is not None and self.vocab.contains_word(word)

    def _norm_syn0(self) -> np.ndarray:
        s = np.asarray(self.syn0)
        n = np.linalg.norm(s, axis=1, keepdims=True)
        return s / np.maximum(n, 1e-12)

    def similarity(self, a: str, b: str) -> float:
        """Cosine similarity (reference: WordVectorsImpl.similarity)."""
        ia, ib = self.vocab.index_of(a), self.vocab.index_of(b)
        if ia < 0 or ib < 0:
            return float("nan")
        s = self._norm_syn0()
        return float(np.dot(s[ia], s[ib]))

    def words_nearest(self, word_or_vec, top_n: int = 10) -> list:
        """Top-N cosine neighbours (reference: wordsNearest)."""
        if isinstance(word_or_vec, str):
            i = self.vocab.index_of(word_or_vec)
            if i < 0:
                return []
            vec = np.asarray(self.syn0[i])
            exclude = {i}
        else:
            vec = np.asarray(word_or_vec)
            exclude = set()
        s = self._norm_syn0()
        v = vec / max(np.linalg.norm(vec), 1e-12)
        sims = s @ v
        order = np.argsort(-sims)
        out = []
        for j in order:
            if int(j) in exclude:
                continue
            out.append((self.vocab.word_at_index(int(j)), float(sims[j])))
            if len(out) >= top_n:
                break
        return out

    def words_nearest_sum(self, positive: list, negative: list,
                          top_n: int = 10) -> list:
        """king - man + woman style analogy (reference: wordsNearestSum)."""
        s = self._norm_syn0()
        vec = np.zeros(self.layer_size, np.float64)
        exclude = set()
        for w in positive:
            i = self.vocab.index_of(w)
            if i >= 0:
                vec += s[i]
                exclude.add(i)
        for w in negative:
            i = self.vocab.index_of(w)
            if i >= 0:
                vec -= s[i]
                exclude.add(i)
        v = vec / max(np.linalg.norm(vec), 1e-12)
        sims = s @ v
        order = np.argsort(-sims)
        out = []
        for j in order:
            if int(j) in exclude:
                continue
            out.append((self.vocab.word_at_index(int(j)), float(sims[j])))
            if len(out) >= top_n:
                break
        return out
