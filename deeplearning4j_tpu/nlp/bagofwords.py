"""Bag-of-words / TF-IDF vectorizers (reference: bagofwords/vectorizer/ —
BagOfWordsVectorizer, TfidfVectorizer over an inverted index)."""

from __future__ import annotations

import math

import numpy as np

from deeplearning4j_tpu.nlp.tokenization import DefaultTokenizerFactory
from deeplearning4j_tpu.nlp.vocab import VocabConstructor


class BagOfWordsVectorizer:
    """Document -> word-count vector (reference:
    bagofwords/vectorizer/BagOfWordsVectorizer.java)."""

    def __init__(self, min_word_frequency: int = 1, tokenizer_factory=None):
        self.min_word_frequency = min_word_frequency
        self.tokenizer_factory = tokenizer_factory or \
            DefaultTokenizerFactory()
        self.vocab = None

    def fit(self, documents) -> "BagOfWordsVectorizer":
        self.vocab = VocabConstructor(
            min_word_frequency=self.min_word_frequency,
            tokenizer_factory=self.tokenizer_factory,
            build_huffman=False).build_vocab(documents)
        return self

    def transform(self, document: str) -> np.ndarray:
        v = np.zeros(self.vocab.num_words(), np.float32)
        for t in self.tokenizer_factory.create(document).tokens():
            i = self.vocab.index_of(t)
            if i >= 0:
                v[i] += 1.0
        return v

    def fit_transform(self, documents) -> np.ndarray:
        documents = list(documents)
        self.fit(documents)
        return np.stack([self.transform(d) for d in documents])


class TfidfVectorizer(BagOfWordsVectorizer):
    """TF-IDF weighting (reference: bagofwords/vectorizer/TfidfVectorizer.java
    — idf = log(N / df), tf raw count)."""

    def fit(self, documents) -> "TfidfVectorizer":
        documents = list(documents)
        super().fit(documents)
        V = self.vocab.num_words()
        df = np.zeros(V, np.float64)
        for d in documents:
            seen = {self.vocab.index_of(t)
                    for t in self.tokenizer_factory.create(d).tokens()}
            for i in seen:
                if i >= 0:
                    df[i] += 1
        n_docs = max(len(documents), 1)
        self.idf = np.where(df > 0, np.log(n_docs / np.maximum(df, 1.0)), 0.0)
        return self

    def transform(self, document: str) -> np.ndarray:
        tf = super().transform(document)
        return (tf * self.idf).astype(np.float32)
