"""Vocabulary: VocabWord, cache, constructor, Huffman coding.

Reference: models/word2vec/VocabWord.java, models/word2vec/wordstore/
(VocabCache SPI, inmemory/AbstractCache.java, VocabConstructor.java:32,168
buildJointVocabulary), models/word2vec/Huffman.java:34 (array-based tree
build with MAX_CODE_LENGTH=40).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

import numpy as np


@dataclass
class VocabWord:
    """reference: models/word2vec/VocabWord.java (word + frequency + huffman
    code/point arrays + index)."""

    word: str
    count: float = 1.0
    index: int = -1
    codes: list = field(default_factory=list)    # huffman binary code
    points: list = field(default_factory=list)   # inner-node indices

    def increment(self, by: float = 1.0) -> None:
        self.count += by


class AbstractCache:
    """In-memory vocab cache (reference: wordstore/inmemory/AbstractCache.java).
    Words are index-addressable after ``update_indices``; index order is
    descending frequency (the reference sorts the same way for Huffman)."""

    def __init__(self):
        self._words: dict[str, VocabWord] = {}
        self._by_index: list[VocabWord] = []
        self.total_word_count = 0.0

    def add_token(self, w: VocabWord) -> None:
        ex = self._words.get(w.word)
        if ex is not None:
            ex.increment(w.count)
        else:
            self._words[w.word] = w

    def increment_count(self, word: str, by: float = 1.0) -> None:
        self._words[word].increment(by)

    def contains_word(self, word: str) -> bool:
        return word in self._words

    def word_for(self, word: str) -> Optional[VocabWord]:
        return self._words.get(word)

    def word_frequency(self, word: str) -> float:
        w = self._words.get(word)
        return w.count if w is not None else 0.0

    def index_of(self, word: str) -> int:
        w = self._words.get(word)
        return w.index if w is not None else -1

    def word_at_index(self, idx: int) -> Optional[str]:
        if 0 <= idx < len(self._by_index):
            return self._by_index[idx].word
        return None

    def element_at_index(self, idx: int) -> VocabWord:
        return self._by_index[idx]

    def num_words(self) -> int:
        return len(self._words)

    def vocab_words(self) -> list:
        return list(self._words.values())

    def remove_below(self, min_frequency: float) -> None:
        self._words = {k: v for k, v in self._words.items()
                       if v.count >= min_frequency}

    def update_indices(self) -> None:
        """Assign indices by descending frequency (stable by word for
        determinism)."""
        self._by_index = sorted(self._words.values(),
                                key=lambda w: (-w.count, w.word))
        for i, w in enumerate(self._by_index):
            w.index = i
        self.total_word_count = float(sum(w.count for w in self._by_index))

    def counts_array(self) -> np.ndarray:
        return np.array([w.count for w in self._by_index], np.float64)


class VocabConstructor:
    """Builds a vocab from sentence iterators (reference:
    wordstore/VocabConstructor.java:32 builder, :168 buildJointVocabulary —
    tokenize + count, prune below minWordFrequency, assign indices, build
    Huffman)."""

    def __init__(self, min_word_frequency: int = 1, tokenizer_factory=None,
                 build_huffman: bool = True):
        from deeplearning4j_tpu.nlp.tokenization import \
            DefaultTokenizerFactory
        self.min_word_frequency = min_word_frequency
        self.tokenizer_factory = tokenizer_factory or \
            DefaultTokenizerFactory()
        self.build_huffman_tree = build_huffman

    def build_vocab(self, sentences) -> AbstractCache:
        cache = AbstractCache()
        for sentence in sentences:
            toks = (list(sentence) if isinstance(sentence, (list, tuple))
                    else self.tokenizer_factory.create(sentence).tokens())
            for tok in toks:
                cache.add_token(VocabWord(tok, 1.0))
        cache.remove_below(self.min_word_frequency)
        cache.update_indices()
        if self.build_huffman_tree and cache.num_words() > 0:
            Huffman(cache).build()
        return cache


class Huffman:
    """Array-based Huffman tree (reference: models/word2vec/Huffman.java:34;
    same two-pointer merge over the frequency-sorted array, max code length
    40). Assigns ``codes``/``points`` on each VocabWord; inner-node index
    space is [0, V-1) as used by hierarchical softmax."""

    MAX_CODE_LENGTH = 40

    def __init__(self, cache: AbstractCache, max_code_length: int = 40):
        self.cache = cache
        self.MAX_CODE_LENGTH = max_code_length

    def build(self) -> None:
        words = [self.cache.element_at_index(i)
                 for i in range(self.cache.num_words())]
        V = len(words)
        if V == 0:
            return
        count = np.empty(2 * V + 1, np.float64)
        count[:V] = [w.count for w in words]
        count[V:] = 1e15
        binary = np.zeros(2 * V + 1, np.int8)
        parent = np.zeros(2 * V + 1, np.int64)

        # words are sorted descending; classic word2vec two-pointer merge
        pos1, pos2 = V - 1, V
        for a in range(V - 1):
            if pos1 >= 0 and count[pos1] < count[pos2]:
                m1, pos1 = pos1, pos1 - 1
            else:
                m1, pos2 = pos2, pos2 + 1
            if pos1 >= 0 and count[pos1] < count[pos2]:
                m2, pos1 = pos1, pos1 - 1
            else:
                m2, pos2 = pos2, pos2 + 1
            count[V + a] = count[m1] + count[m2]
            parent[m1] = V + a
            parent[m2] = V + a
            binary[m2] = 1

        for a, w in enumerate(words):
            code, point = [], []
            b = a
            while b != 2 * V - 2:
                code.append(int(binary[b]))
                point.append(b)
                b = parent[b]
                if len(code) > self.MAX_CODE_LENGTH:
                    break
            # reverse; points are inner-node ids offset to [0, V-1)
            w.codes = code[::-1]
            w.points = [V - 2] + [p - V for p in point[::-1][:-1]] \
                if len(point) > 0 else []
            # reference stores root first then the path inner nodes;
            # path length == code length
            w.points = w.points[:len(w.codes)]
