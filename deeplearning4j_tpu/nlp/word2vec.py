"""Word2Vec facade (reference: models/word2vec/Word2Vec.java:32 — a
SequenceVectors specialisation over sentence iterators + tokenizer)."""

from __future__ import annotations

from deeplearning4j_tpu.nlp.sequence_vectors import SequenceVectors


class Word2Vec(SequenceVectors):
    """Same builder surface as the reference: layer_size, window_size,
    min_word_frequency, negative_sample, hs, subsampling, epochs/iterations.

    >>> w2v = Word2Vec(layer_size=50, window=5, negative=5)
    >>> w2v.fit(CollectionSentenceIterator(sentences))
    >>> w2v.words_nearest("day", 5)
    """

    def __init__(self, **kw):
        kw.setdefault("elements_algorithm", "skipgram")
        super().__init__(**kw)

    # reference-name aliases
    def get_word_vector(self, word):
        return self.word_vector(word)

    def vocab_size(self) -> int:
        return self.vocab.num_words() if self.vocab is not None else 0
