"""Word-vector serialization (reference:
models/embeddings/loader/WordVectorSerializer.java — Google word2vec binary
format read/write + plain-text format)."""

from __future__ import annotations

import struct

import numpy as np


def write_word2vec_binary(model, path: str) -> None:
    """Google word2vec .bin format: header 'V D\\n', then per word:
    'word '<D float32 little-endian>'\\n' (reference:
    WordVectorSerializer.writeWordVectors binary path)."""
    syn0 = np.asarray(model.syn0, np.float32)
    # vocab size, NOT syn0.shape[0]: sharded tables carry mesh-padding rows
    # past the vocabulary (nlp/distributed.py)
    V, D = model.vocab.num_words(), syn0.shape[1]
    with open(path, "wb") as f:
        f.write(f"{V} {D}\n".encode())
        for i in range(V):
            word = model.vocab.word_at_index(i)
            f.write(word.encode("utf-8") + b" ")
            f.write(syn0[i].astype("<f4").tobytes())
            f.write(b"\n")


def read_word2vec_binary(path: str):
    """-> (words list, [V, D] float32). Tolerates the optional trailing
    newline per row (both classic layouts exist in the wild)."""
    with open(path, "rb") as f:
        header = b""
        while not header.endswith(b"\n"):
            header += f.read(1)
        V, D = map(int, header.split())
        words, vecs = [], np.empty((V, D), np.float32)
        for i in range(V):
            w = b""
            while True:
                c = f.read(1)
                if c in (b" ", b""):
                    break
                if c != b"\n":
                    w += c
            words.append(w.decode("utf-8", errors="replace"))
            vecs[i] = np.frombuffer(f.read(4 * D), "<f4")
    return words, vecs


def write_word_vectors_text(model, path: str) -> None:
    """Plain text: 'word v1 v2 ...' per line (reference:
    WordVectorSerializer.writeWordVectors)."""
    syn0 = np.asarray(model.syn0)
    with open(path, "w", encoding="utf-8") as f:
        # vocab size, not syn0.shape[0] (mesh-padding rows — see binary path)
        for i in range(model.vocab.num_words()):
            vec = " ".join(f"{x:.6f}" for x in syn0[i])
            f.write(f"{model.vocab.word_at_index(i)} {vec}\n")


def read_word_vectors_text(path: str):
    words, rows = [], []
    with open(path, encoding="utf-8") as f:
        for i, line in enumerate(f):
            parts = line.split()  # whitespace split also strips CRLF \r
            if len(parts) < 2:
                continue
            if i == 0 and len(parts) == 2 and all(p.isdigit()
                                                  for p in parts):
                continue  # optional gensim-style "V D" count header
            words.append(parts[0])
            rows.append([float(x) for x in parts[1:]])
    return words, np.asarray(rows, np.float32)


def load_word2vec(path: str, binary: bool = True):
    """-> a queryable Word2Vec with vocab + vectors, no training state
    (reference: WordVectorSerializer.loadGoogleModel)."""
    from deeplearning4j_tpu.nlp.vocab import AbstractCache, VocabWord
    from deeplearning4j_tpu.nlp.word2vec import Word2Vec

    words, vecs = (read_word2vec_binary(path) if binary
                   else read_word_vectors_text(path))
    import jax.numpy as jnp

    m = Word2Vec(layer_size=vecs.shape[1])
    cache = AbstractCache()
    # preserve file order as index order: descending pseudo-frequency
    for r, w in enumerate(words):
        cache.add_token(VocabWord(w, count=float(len(words) - r)))
    cache.update_indices()
    m.vocab = cache
    order = np.asarray([cache.index_of(w) for w in words])
    syn0 = np.empty_like(vecs)
    syn0[order] = vecs
    m.syn0 = jnp.asarray(syn0)
    return m
