"""Text pipeline: tokenizers, preprocessors, sentence iterators.

Reference: deeplearning4j-nlp text/tokenization/tokenizerfactory/
(DefaultTokenizerFactory, TokenizerFactory SPI), tokenizer/preprocessor/
(CommonPreprocessor, EndingPreProcessor), text/sentenceiterator/
(CollectionSentenceIterator, FileSentenceIterator, LineSentenceIterator,
LabelAwareSentenceIterator). Vendored CJK analyzers (ansj/kuromoji, ~17k LoC
of third-party Java) are out of scope; the TokenizerFactory SPI is the hook
where equivalents would plug in.
"""

from __future__ import annotations

import os
import re
import string
from typing import Iterable, Optional


class TokenPreProcess:
    """reference: tokenization/tokenizer/TokenPreProcess.java"""

    def pre_process(self, token: str) -> str:
        raise NotImplementedError


class CommonPreprocessor(TokenPreProcess):
    """Lowercase + strip punctuation/digits-adjacent junk (reference:
    tokenization/tokenizer/preprocessor/CommonPreprocessor.java)."""

    _PUNCT = re.compile(r"[\d.:,\"'()\[\]|/?!;]+")

    def pre_process(self, token: str) -> str:
        return self._PUNCT.sub("", token.lower())


class LowCasePreProcessor(TokenPreProcess):
    def pre_process(self, token: str) -> str:
        return token.lower()


class EndingPreProcessor(TokenPreProcess):
    """Crude stemmer (reference: preprocessor/EndingPreProcessor.java)."""

    def pre_process(self, token: str) -> str:
        for end in ("s", "ly", "ed", "ing", "ness"):
            if token.endswith(end) and len(token) > len(end) + 2:
                return token[:-len(end)]
        return token


class Tokenizer:
    """reference: tokenization/tokenizer/Tokenizer.java (iterator API
    collapsed to a list-returning ``tokens()``)"""

    def __init__(self, text: str, preprocessor: Optional[TokenPreProcess]):
        self._tokens = [t for t in text.split() if t]
        self._pre = preprocessor

    def tokens(self) -> list:
        if self._pre is None:
            return list(self._tokens)
        out = []
        for t in self._tokens:
            p = self._pre.pre_process(t)
            if p:
                out.append(p)
        return out

    def count_tokens(self) -> int:
        return len(self._tokens)


class TokenizerFactory:
    """reference: tokenizerfactory/TokenizerFactory.java SPI"""

    def create(self, text: str) -> Tokenizer:
        raise NotImplementedError

    def set_token_pre_processor(self, pre: TokenPreProcess) -> None:
        raise NotImplementedError


class DefaultTokenizerFactory(TokenizerFactory):
    """Whitespace tokenizer (reference:
    tokenizerfactory/DefaultTokenizerFactory.java)."""

    def __init__(self, preprocessor: Optional[TokenPreProcess] = None):
        self._pre = preprocessor

    def create(self, text: str) -> Tokenizer:
        return Tokenizer(text, self._pre)

    def set_token_pre_processor(self, pre: TokenPreProcess) -> None:
        self._pre = pre


class NGramTokenizerFactory(TokenizerFactory):
    """n-gram over a base tokenizer (reference:
    tokenizerfactory/NGramTokenizerFactory.java)."""

    def __init__(self, base: TokenizerFactory, min_n: int, max_n: int):
        self.base = base
        self.min_n = min_n
        self.max_n = max_n

    def create(self, text: str) -> Tokenizer:
        toks = self.base.create(text).tokens()
        grams = []
        for n in range(self.min_n, self.max_n + 1):
            for i in range(len(toks) - n + 1):
                grams.append(" ".join(toks[i:i + n]))
        t = Tokenizer("", None)
        t._tokens = grams
        return t

    def set_token_pre_processor(self, pre: TokenPreProcess) -> None:
        self.base.set_token_pre_processor(pre)


# ------------------------------------------------------------------ iterators
class SentenceIterator:
    """reference: text/sentenceiterator/SentenceIterator.java"""

    def __iter__(self):
        self.reset()
        return self._gen()

    def _gen(self):
        raise NotImplementedError

    def reset(self) -> None:
        pass


class CollectionSentenceIterator(SentenceIterator):
    def __init__(self, sentences: Iterable[str]):
        self.sentences = list(sentences)

    def _gen(self):
        yield from self.sentences


class LineSentenceIterator(SentenceIterator):
    """One sentence per line of a file (reference:
    sentenceiterator/LineSentenceIterator.java)."""

    def __init__(self, path: str):
        self.path = path

    def _gen(self):
        with open(self.path, encoding="utf-8", errors="ignore") as f:
            for line in f:
                line = line.strip()
                if line:
                    yield line


class FileSentenceIterator(SentenceIterator):
    """All files under a directory, line per sentence (reference:
    sentenceiterator/FileSentenceIterator.java)."""

    def __init__(self, directory: str):
        self.directory = directory

    def _gen(self):
        for root, _, files in os.walk(self.directory):
            for fn in sorted(files):
                with open(os.path.join(root, fn), encoding="utf-8",
                          errors="ignore") as f:
                    for line in f:
                        line = line.strip()
                        if line:
                            yield line


class LabelledDocument:
    """reference: text/documentiterator/LabelledDocument.java"""

    def __init__(self, content: str, labels):
        self.content = content
        self.labels = labels if isinstance(labels, (list, tuple)) \
            else [labels]


class LabelAwareIterator:
    """Documents with labels, for ParagraphVectors (reference:
    text/documentiterator/LabelAwareIterator.java)."""

    def __init__(self, documents: Iterable[LabelledDocument]):
        self.documents = list(documents)

    def __iter__(self):
        return iter(self.documents)

    def reset(self):
        pass
