"""Text pipeline: tokenizers, preprocessors, sentence iterators.

Reference: deeplearning4j-nlp text/tokenization/tokenizerfactory/
(DefaultTokenizerFactory, TokenizerFactory SPI), tokenizer/preprocessor/
(CommonPreprocessor, EndingPreProcessor), text/sentenceiterator/
(CollectionSentenceIterator, FileSentenceIterator, LineSentenceIterator,
LabelAwareSentenceIterator). The reference's vendored dictionary analyzers
(ansj/kuromoji, ~19.7k LoC of third-party Java) stay out of scope, but a
first-party ``CjkTokenizerFactory`` (script-aware character-bigram
segmentation) covers the basic CJK capability behind the same
TokenizerFactory SPI; a dictionary segmenter plugs in the same way.
"""

from __future__ import annotations

import os
import re
import string
from typing import Iterable, Optional


class TokenPreProcess:
    """reference: tokenization/tokenizer/TokenPreProcess.java"""

    def pre_process(self, token: str) -> str:
        raise NotImplementedError


class CommonPreprocessor(TokenPreProcess):
    """Lowercase + strip punctuation/digits-adjacent junk (reference:
    tokenization/tokenizer/preprocessor/CommonPreprocessor.java)."""

    _PUNCT = re.compile(r"[\d.:,\"'()\[\]|/?!;]+")

    def pre_process(self, token: str) -> str:
        return self._PUNCT.sub("", token.lower())


class LowCasePreProcessor(TokenPreProcess):
    def pre_process(self, token: str) -> str:
        return token.lower()


class EndingPreProcessor(TokenPreProcess):
    """Crude stemmer (reference: preprocessor/EndingPreProcessor.java)."""

    def pre_process(self, token: str) -> str:
        for end in ("s", "ly", "ed", "ing", "ness"):
            if token.endswith(end) and len(token) > len(end) + 2:
                return token[:-len(end)]
        return token


class Tokenizer:
    """reference: tokenization/tokenizer/Tokenizer.java (iterator API
    collapsed to a list-returning ``tokens()``)"""

    def __init__(self, text: str, preprocessor: Optional[TokenPreProcess]):
        self._tokens = [t for t in text.split() if t]
        self._pre = preprocessor

    def tokens(self) -> list:
        if self._pre is None:
            return list(self._tokens)
        out = []
        for t in self._tokens:
            p = self._pre.pre_process(t)
            if p:
                out.append(p)
        return out

    def count_tokens(self) -> int:
        return len(self._tokens)


class TokenizerFactory:
    """reference: tokenizerfactory/TokenizerFactory.java SPI"""

    def create(self, text: str) -> Tokenizer:
        raise NotImplementedError

    def set_token_pre_processor(self, pre: TokenPreProcess) -> None:
        raise NotImplementedError


class DefaultTokenizerFactory(TokenizerFactory):
    """Whitespace tokenizer (reference:
    tokenizerfactory/DefaultTokenizerFactory.java)."""

    def __init__(self, preprocessor: Optional[TokenPreProcess] = None):
        self._pre = preprocessor

    def create(self, text: str) -> Tokenizer:
        return Tokenizer(text, self._pre)

    def set_token_pre_processor(self, pre: TokenPreProcess) -> None:
        self._pre = pre


class _CjkSegmentingTokenizer(Tokenizer):
    """Script-aware tokenizer: CJK spans (which carry no whitespace word
    boundaries) are segmented into overlapping character bigrams — the
    standard statistical fallback the reference's vendored analyzers
    (kuromoji for Japanese, smartcn-style segmenters for Chinese) improve
    on with dictionaries; non-CJK spans keep whitespace tokenization and
    CJK punctuation acts as a token boundary (CommonPreprocessor's ASCII
    regex cannot strip it downstream). First-party and dependency-free;
    plug a dictionary segmenter through the same TokenizerFactory SPI
    when higher quality is needed."""

    _CJK_RANGES = (
        (0x3005, 0x3007),    # 々 iteration mark, 〆, 〇
        (0x3040, 0x30FF),    # hiragana + katakana
        (0x31F0, 0x31FF),    # katakana phonetic extensions
        (0x3400, 0x4DBF),    # CJK ext A
        (0x4E00, 0x9FFF),    # CJK unified
        (0xAC00, 0xD7AF),    # hangul syllables
        (0xF900, 0xFAFF),    # CJK compat ideographs
        (0xFF66, 0xFF9F),    # halfwidth katakana
        (0x20000, 0x2FA1F),  # CJK ext B..F + compat supplement
    )
    # ideographic punctuation / fullwidth sentence marks: boundaries,
    # never tokens (they would otherwise flood the vocab — ASCII-focused
    # preprocessors cannot strip them)
    _CJK_PUNCT = set("\u3001\u3002\u30fb\u30fc\uff01\uff08\uff09"
                     "\uff0c\uff0e\uff1a\uff1b\uff1f\u300c\u300d"
                     "\u300e\u300f\u3008\u3009\u2026\u301c\uff5e")

    @classmethod
    def _char_class(cls, ch: str) -> str:
        if ch in cls._CJK_PUNCT:
            return "punct"
        o = ord(ch)
        if any(lo <= o <= hi for lo, hi in cls._CJK_RANGES):
            return "cjk"
        return "other"

    def __init__(self, text: str, preprocessor: Optional[TokenPreProcess]):
        import itertools

        tokens = []
        for chunk in text.split():
            for cls_, grp in itertools.groupby(chunk, key=self._char_class):
                run = "".join(grp)
                if cls_ == "punct":
                    continue  # boundary, not a token
                if cls_ == "other" or len(run) == 1:
                    tokens.append(run)
                else:  # overlapping character bigrams
                    tokens.extend(run[i:i + 2]
                                  for i in range(len(run) - 1))
        self._tokens = [t for t in tokens if t]
        self._pre = preprocessor


class CjkTokenizerFactory(TokenizerFactory):
    """Character-bigram CJK tokenizer factory (the first-party analog of
    the reference's vendored tokenizers.cjk / kuromoji analyzers, behind
    the same TokenizerFactory SPI)."""

    def __init__(self, preprocessor: Optional[TokenPreProcess] = None):
        self._pre = preprocessor

    def create(self, text: str) -> Tokenizer:
        return _CjkSegmentingTokenizer(text, self._pre)

    def set_token_pre_processor(self, pre: TokenPreProcess) -> None:
        self._pre = pre


class NGramTokenizerFactory(TokenizerFactory):
    """n-gram over a base tokenizer (reference:
    tokenizerfactory/NGramTokenizerFactory.java)."""

    def __init__(self, base: TokenizerFactory, min_n: int, max_n: int):
        self.base = base
        self.min_n = min_n
        self.max_n = max_n

    def create(self, text: str) -> Tokenizer:
        toks = self.base.create(text).tokens()
        grams = []
        for n in range(self.min_n, self.max_n + 1):
            for i in range(len(toks) - n + 1):
                grams.append(" ".join(toks[i:i + n]))
        t = Tokenizer("", None)
        t._tokens = grams
        return t

    def set_token_pre_processor(self, pre: TokenPreProcess) -> None:
        self.base.set_token_pre_processor(pre)


# ------------------------------------------------------------------ iterators
class SentenceIterator:
    """reference: text/sentenceiterator/SentenceIterator.java"""

    def __iter__(self):
        self.reset()
        return self._gen()

    def _gen(self):
        raise NotImplementedError

    def reset(self) -> None:
        pass


class CollectionSentenceIterator(SentenceIterator):
    def __init__(self, sentences: Iterable[str]):
        self.sentences = list(sentences)

    def _gen(self):
        yield from self.sentences


class LineSentenceIterator(SentenceIterator):
    """One sentence per line of a file (reference:
    sentenceiterator/LineSentenceIterator.java)."""

    def __init__(self, path: str):
        self.path = path

    def _gen(self):
        with open(self.path, encoding="utf-8", errors="ignore") as f:
            for line in f:
                line = line.strip()
                if line:
                    yield line


class FileSentenceIterator(SentenceIterator):
    """All files under a directory, line per sentence (reference:
    sentenceiterator/FileSentenceIterator.java)."""

    def __init__(self, directory: str):
        self.directory = directory

    def _gen(self):
        for root, _, files in os.walk(self.directory):
            for fn in sorted(files):
                with open(os.path.join(root, fn), encoding="utf-8",
                          errors="ignore") as f:
                    for line in f:
                        line = line.strip()
                        if line:
                            yield line


class LabelledDocument:
    """reference: text/documentiterator/LabelledDocument.java"""

    def __init__(self, content: str, labels):
        self.content = content
        self.labels = labels if isinstance(labels, (list, tuple)) \
            else [labels]


class LabelAwareIterator:
    """Documents with labels, for ParagraphVectors (reference:
    text/documentiterator/LabelAwareIterator.java)."""

    def __init__(self, documents: Iterable[LabelledDocument]):
        self.documents = list(documents)

    def __iter__(self):
        return iter(self.documents)

    def reset(self):
        pass
