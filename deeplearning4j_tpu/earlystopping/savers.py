"""Model savers (reference: earlystopping/saver/).

``InMemoryModelSaver`` keeps a clone; ``LocalFileModelSaver`` writes
bestModel.bin / latestModel.bin zips via the model serializer (reference:
LocalFileModelSaver.java:44-55 uses the same two file names).
"""

from __future__ import annotations

import os


class EarlyStoppingModelSaver:
    def save_best_model(self, net, score: float) -> None:
        raise NotImplementedError

    def save_latest_model(self, net, score: float) -> None:
        raise NotImplementedError

    def get_best_model(self):
        raise NotImplementedError

    def get_latest_model(self):
        raise NotImplementedError


class InMemoryModelSaver(EarlyStoppingModelSaver):
    """reference: saver/InMemoryModelSaver.java"""

    def __init__(self):
        self._best = None
        self._latest = None

    def save_best_model(self, net, score: float) -> None:
        self._best = net.clone()

    def save_latest_model(self, net, score: float) -> None:
        self._latest = net.clone()

    def get_best_model(self):
        return self._best

    def get_latest_model(self):
        return self._latest


class LocalFileModelSaver(EarlyStoppingModelSaver):
    """reference: saver/LocalFileModelSaver.java (bestModel.bin /
    latestModel.bin in a directory). Files are our model zips."""

    def __init__(self, directory: str):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)

    def _path(self, name: str) -> str:
        return os.path.join(self.directory, name)

    def save_best_model(self, net, score: float) -> None:
        from deeplearning4j_tpu.utils.model_serializer import save_model
        save_model(net, self._path("bestModel.bin"))

    def save_latest_model(self, net, score: float) -> None:
        from deeplearning4j_tpu.utils.model_serializer import save_model
        save_model(net, self._path("latestModel.bin"))

    def _load(self, name: str):
        from deeplearning4j_tpu.utils.model_serializer import load_model
        p = self._path(name)
        return load_model(p) if os.path.exists(p) else None

    def get_best_model(self):
        return self._load("bestModel.bin")

    def get_latest_model(self):
        return self._load("latestModel.bin")
