"""Early stopping (reference: deeplearning4j-nn earlystopping/, 22 files).

- ``EarlyStoppingConfiguration`` — conditions + score calculator + saver
  (reference: earlystopping/EarlyStoppingConfiguration.java builder)
- termination conditions (reference: earlystopping/termination/*)
- ``DataSetLossCalculator`` (reference: scorecalc/DataSetLossCalculator.java,
  DataSetLossCalculatorCG.java — one class here, both net types share score())
- savers (reference: saver/InMemoryModelSaver.java, LocalFileModelSaver.java)
- ``EarlyStoppingTrainer`` — the fit loop (reference:
  trainer/BaseEarlyStoppingTrainer.java:76-220). Works for MultiLayerNetwork
  and ComputationGraph alike (the reference needs a separate
  EarlyStoppingGraphTrainer; here both expose the same fit/score contract).
"""

from deeplearning4j_tpu.earlystopping.config import (
    EarlyStoppingConfiguration,
    EarlyStoppingResult,
)
from deeplearning4j_tpu.earlystopping.savers import (
    InMemoryModelSaver,
    LocalFileModelSaver,
)
from deeplearning4j_tpu.earlystopping.scorecalc import (
    DataSetLossCalculator,
    EvaluationScoreCalculator,
)
from deeplearning4j_tpu.earlystopping.termination import (
    BestScoreEpochTerminationCondition,
    InvalidScoreIterationTerminationCondition,
    MaxEpochsTerminationCondition,
    MaxScoreIterationTerminationCondition,
    MaxTimeIterationTerminationCondition,
    ScoreImprovementEpochTerminationCondition,
)
from deeplearning4j_tpu.earlystopping.trainer import (
    EarlyStoppingGraphTrainer,
    EarlyStoppingTrainer,
)

__all__ = [
    "EarlyStoppingConfiguration", "EarlyStoppingResult",
    "InMemoryModelSaver", "LocalFileModelSaver",
    "DataSetLossCalculator", "EvaluationScoreCalculator",
    "MaxEpochsTerminationCondition", "BestScoreEpochTerminationCondition",
    "ScoreImprovementEpochTerminationCondition",
    "MaxTimeIterationTerminationCondition",
    "MaxScoreIterationTerminationCondition",
    "InvalidScoreIterationTerminationCondition",
    "EarlyStoppingTrainer", "EarlyStoppingGraphTrainer",
]
