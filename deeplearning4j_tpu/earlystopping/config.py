"""EarlyStoppingConfiguration + EarlyStoppingResult (reference:
earlystopping/EarlyStoppingConfiguration.java, EarlyStoppingResult.java)."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional


class TerminationReason:
    """reference: EarlyStoppingResult.TerminationReason enum"""

    ERROR = "Error"
    ITERATION_TERMINATION_CONDITION = "IterationTerminationCondition"
    EPOCH_TERMINATION_CONDITION = "EpochTerminationCondition"


@dataclass
class EarlyStoppingResult:
    termination_reason: str
    termination_details: str
    score_vs_epoch: dict
    best_model_epoch: int
    best_model_score: float
    total_epochs: int
    best_model: object

    def get_best_model(self):
        return self.best_model


@dataclass
class EarlyStoppingConfiguration:
    """Builder-free dataclass config (the reference's Builder maps 1:1 to
    keyword arguments)."""

    epoch_termination_conditions: list = field(default_factory=list)
    iteration_termination_conditions: list = field(default_factory=list)
    score_calculator: Optional[object] = None
    model_saver: Optional[object] = None
    evaluate_every_n_epochs: int = 1
    save_last_model: bool = False

    def __post_init__(self):
        if self.model_saver is None:
            from deeplearning4j_tpu.earlystopping.savers import \
                InMemoryModelSaver
            self.model_saver = InMemoryModelSaver()
