"""Termination conditions (reference: earlystopping/termination/ — 9 classes).

Epoch conditions see (epoch, score); iteration conditions see the per-minibatch
score. ``initialize()`` resets any internal state before a fit() run.
"""

from __future__ import annotations

import math
import time
from dataclasses import dataclass


class EpochTerminationCondition:
    """reference: termination/EpochTerminationCondition.java"""

    def initialize(self) -> None:
        pass

    def terminate(self, epoch: int, score: float) -> bool:
        raise NotImplementedError


class IterationTerminationCondition:
    """reference: termination/IterationTerminationCondition.java"""

    def initialize(self) -> None:
        pass

    def terminate(self, last_score: float) -> bool:
        raise NotImplementedError


@dataclass
class MaxEpochsTerminationCondition(EpochTerminationCondition):
    """Stop after N epochs (reference: MaxEpochsTerminationCondition.java)."""

    max_epochs: int

    def terminate(self, epoch: int, score: float) -> bool:
        return epoch + 1 >= self.max_epochs

    def __str__(self):
        return f"MaxEpochsTerminationCondition({self.max_epochs})"


@dataclass
class BestScoreEpochTerminationCondition(EpochTerminationCondition):
    """Stop once the score is at/below a target (reference:
    BestScoreEpochTerminationCondition.java — 'lesser than or equal')."""

    best_expected_score: float

    def terminate(self, epoch: int, score: float) -> bool:
        return score <= self.best_expected_score

    def __str__(self):
        return f"BestScoreEpochTerminationCondition({self.best_expected_score})"


@dataclass
class ScoreImprovementEpochTerminationCondition(EpochTerminationCondition):
    """Stop after N epochs with no (sufficient) improvement (reference:
    ScoreImprovementEpochTerminationCondition.java)."""

    max_epochs_without_improvement: int
    min_improvement: float = 0.0

    def initialize(self) -> None:
        self._best = None
        self._epochs_without = 0

    def terminate(self, epoch: int, score: float) -> bool:
        if self._best is None or self._best - score > self.min_improvement:
            self._best = score
            self._epochs_without = 0
            return False
        self._epochs_without += 1
        return self._epochs_without > self.max_epochs_without_improvement

    def __str__(self):
        return ("ScoreImprovementEpochTerminationCondition("
                f"{self.max_epochs_without_improvement}, "
                f"{self.min_improvement})")


@dataclass
class MaxTimeIterationTerminationCondition(IterationTerminationCondition):
    """Elapsed-time budget, measured on the monotonic clock (reference:
    MaxTimeIterationTerminationCondition.java)."""

    max_seconds: float

    def initialize(self) -> None:
        # monotonic: a wall-clock step (NTP, VM migration) must neither
        # fire termination early nor extend the budget
        self._start = time.monotonic()

    def terminate(self, last_score: float) -> bool:
        return (time.monotonic() - self._start) >= self.max_seconds

    def __str__(self):
        return f"MaxTimeIterationTerminationCondition({self.max_seconds}s)"


@dataclass
class MaxScoreIterationTerminationCondition(IterationTerminationCondition):
    """Stop if score exceeds a ceiling — divergence guard (reference:
    MaxScoreIterationTerminationCondition.java)."""

    max_score: float

    def terminate(self, last_score: float) -> bool:
        return last_score > self.max_score

    def __str__(self):
        return f"MaxScoreIterationTerminationCondition({self.max_score})"


class InvalidScoreIterationTerminationCondition(IterationTerminationCondition):
    """Stop on NaN/Inf score (reference:
    InvalidScoreIterationTerminationCondition.java)."""

    def terminate(self, last_score: float) -> bool:
        return math.isnan(last_score) or math.isinf(last_score)

    def __str__(self):
        return "InvalidScoreIterationTerminationCondition()"
