"""Score calculators (reference: earlystopping/scorecalc/).

``DataSetLossCalculator`` averages the model loss over a validation iterator
(reference: DataSetLossCalculator.java — example- or batch-averaged; and
DataSetLossCalculatorCG.java — one class covers both net types here since
MultiLayerNetwork and ComputationGraph share score()).
"""

from __future__ import annotations


class ScoreCalculator:
    def calculate_score(self, net) -> float:
        raise NotImplementedError


class DataSetLossCalculator(ScoreCalculator):
    def __init__(self, iterator, average: bool = True):
        self.iterator = iterator
        self.average = average

    def calculate_score(self, net) -> float:
        if hasattr(self.iterator, "reset"):
            self.iterator.reset()
        total = 0.0
        n = 0
        for ds in self.iterator:
            b = ds.num_examples()
            total += net.score(ds) * (b if self.average else 1.0)
            n += b if self.average else 1
        return total / max(n, 1)


class EvaluationScoreCalculator(ScoreCalculator):
    """Score = 1 - accuracy on a validation iterator, so 'minimize score'
    maximizes accuracy (the reference gained this class post-0.8; provided for
    API completeness)."""

    def __init__(self, iterator):
        self.iterator = iterator

    def calculate_score(self, net) -> float:
        if hasattr(self.iterator, "reset"):
            self.iterator.reset()
        ev = net.evaluate(self.iterator)
        return 1.0 - ev.accuracy()
