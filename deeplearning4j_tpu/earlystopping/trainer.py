"""Early-stopping fit loop (reference:
earlystopping/trainer/BaseEarlyStoppingTrainer.java:76-220,
EarlyStoppingTrainer.java, EarlyStoppingGraphTrainer.java).

The loop: per epoch, fit every minibatch (checking iteration conditions on the
minibatch score), then every ``evaluate_every_n_epochs`` compute the
validation score, track/save the best model, and check epoch conditions.
"""

from __future__ import annotations

import math

from deeplearning4j_tpu.earlystopping.config import (
    EarlyStoppingConfiguration,
    EarlyStoppingResult,
    TerminationReason,
)


class EarlyStoppingListener:
    """reference: earlystopping/listener/EarlyStoppingListener.java"""

    def on_start(self, config, net) -> None:
        pass

    def on_epoch(self, epoch: int, score: float, config, net) -> None:
        pass

    def on_completion(self, result) -> None:
        pass


class EarlyStoppingTrainer:
    def __init__(self, config: EarlyStoppingConfiguration, net, train_iterator,
                 listener: EarlyStoppingListener | None = None,
                 health_guard=None):
        self.config = config
        self.net = net
        self.iterator = train_iterator
        self.listener = listener
        # health guard OFF by default here, unlike net.fit: this loop calls
        # fit() once per minibatch (a fresh default policy each call would
        # be stateless), and early stopping has its own divergence handling
        # — InvalidScoreIterationTerminationCondition terminates the run on
        # the raw NaN/inf score the guard deliberately leaves visible. Pass
        # a configured optimize.health.HealthPolicy to enable skip-step
        # protection under early stopping (one policy, carried across the
        # per-minibatch fit calls).
        self.health_guard = health_guard

    def fit(self) -> EarlyStoppingResult:
        cfg = self.config
        for c in cfg.epoch_termination_conditions:
            c.initialize()
        for c in cfg.iteration_termination_conditions:
            c.initialize()
        if self.listener:
            self.listener.on_start(cfg, self.net)

        score_vs_epoch: dict = {}
        best_score = math.inf
        best_epoch = -1
        epoch = 0
        while True:
            if hasattr(self.iterator, "reset"):
                self.iterator.reset()
            terminate_reason = None
            try:
                for ds in self.iterator:
                    self.net.fit(ds, health_guard=self.health_guard)
                    last = self.net.score_value
                    for c in cfg.iteration_termination_conditions:
                        if c.terminate(last):
                            terminate_reason = c
                            break
                    if terminate_reason is not None:
                        break
            except Exception as e:  # noqa: BLE001 — reference returns Error result
                result = EarlyStoppingResult(
                    TerminationReason.ERROR, repr(e), score_vs_epoch,
                    best_epoch, best_score, epoch,
                    cfg.model_saver.get_best_model())
                if self.listener:
                    self.listener.on_completion(result)
                return result

            if terminate_reason is not None:
                if cfg.save_last_model:
                    cfg.model_saver.save_latest_model(self.net, 0.0)
                result = EarlyStoppingResult(
                    TerminationReason.ITERATION_TERMINATION_CONDITION,
                    str(terminate_reason), score_vs_epoch, best_epoch,
                    best_score, epoch, cfg.model_saver.get_best_model())
                if self.listener:
                    self.listener.on_completion(result)
                return result

            epoch += 1
            if (epoch - 1) % cfg.evaluate_every_n_epochs == 0 or epoch == 1:
                sc = cfg.score_calculator
                score = 0.0 if sc is None else sc.calculate_score(self.net)
                score_vs_epoch[epoch - 1] = score
                if sc is not None and score < best_score:
                    best_score = score
                    best_epoch = epoch - 1  # 0-based, keys score_vs_epoch
                    cfg.model_saver.save_best_model(self.net, score)
                if self.listener:
                    self.listener.on_epoch(epoch, score, cfg, self.net)
                epoch_term = None
                for c in cfg.epoch_termination_conditions:
                    if c.terminate(epoch - 1, score):
                        epoch_term = c
                        break
                if epoch_term is not None:
                    if cfg.save_last_model:
                        cfg.model_saver.save_latest_model(self.net, score)
                    best = cfg.model_saver.get_best_model()
                    result = EarlyStoppingResult(
                        TerminationReason.EPOCH_TERMINATION_CONDITION,
                        str(epoch_term), score_vs_epoch, best_epoch,
                        best_score, epoch,
                        best if best is not None else self.net)
                    if self.listener:
                        self.listener.on_completion(result)
                    return result


# Graph nets share the same contract; alias for reference-API parity
# (reference: trainer/EarlyStoppingGraphTrainer.java).
EarlyStoppingGraphTrainer = EarlyStoppingTrainer
