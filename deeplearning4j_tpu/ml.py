"""Estimator-protocol wrappers: the dl4j-spark-ml analog.

Reference: deeplearning4j-scaleout spark/dl4j-spark-ml —
SparkDl4jNetwork.scala wraps a network as a Spark ML Pipeline
``Estimator``/``Model`` so it composes with that ecosystem's tooling.
The Python ecosystem's pipeline protocol is scikit-learn's
fit/predict/transform + get_params/set_params duck type — implemented
here WITHOUT importing sklearn (works standalone, and drops into
sklearn Pipelines/GridSearchCV when sklearn is present).
"""

from __future__ import annotations

from typing import Callable, Optional

import numpy as np

try:  # real sklearn bases when available (tags/clone/check_is_fitted
    # integration); plain-object fallback keeps this module standalone
    from sklearn.base import BaseEstimator, ClassifierMixin, RegressorMixin
except ImportError:  # pragma: no cover - sklearn is in the image
    class BaseEstimator:  # type: ignore[no-redef]
        pass

    class ClassifierMixin:  # type: ignore[no-redef]
        pass

    class RegressorMixin:  # type: ignore[no-redef]
        pass


class DL4JEstimator(BaseEstimator):
    """Base estimator: wraps a network-builder callable.

    ``conf_factory``: () -> built configuration; the network is
    constructed fresh on each fit (sklearn semantics: fit resets)."""

    def __init__(self, conf_factory: Callable, epochs: int = 10,
                 batch_size: int = 32):
        self.conf_factory = conf_factory
        self.epochs = epochs
        self.batch_size = batch_size
        self.net_ = None

    # sklearn protocol -----------------------------------------------------
    def get_params(self, deep: bool = True) -> dict:
        return {"conf_factory": self.conf_factory, "epochs": self.epochs,
                "batch_size": self.batch_size}

    def set_params(self, **params) -> "DL4JEstimator":
        valid = self.get_params()
        for k, v in params.items():
            if k not in valid:  # constructor params only (sklearn contract)
                raise ValueError(f"Invalid parameter {k}")
            setattr(self, k, v)
        return self

    def _fit_net(self, x, y):
        from deeplearning4j_tpu.datasets.dataset import DataSet
        from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator
        from deeplearning4j_tpu.nn.graph import ComputationGraph
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        conf = self.conf_factory()
        net_cls = (ComputationGraph if hasattr(conf, "vertices")
                   else MultiLayerNetwork)
        self.net_ = net_cls(conf).init()
        self.net_.fit(ListDataSetIterator(DataSet(x, y),
                                          batch_size=self.batch_size),
                      epochs=self.epochs)
        return self

    def _check_fitted(self):
        if self.net_ is None:
            raise RuntimeError("Estimator is not fitted; call fit first")


class DL4JClassifier(ClassifierMixin, DL4JEstimator):
    """Classifier over a softmax-output network. y: class indices [N] or
    one-hot [N, C]."""

    def fit(self, x, y) -> "DL4JClassifier":
        x = np.asarray(x)
        y = np.asarray(y)
        if y.ndim == 1:
            self.classes_ = np.unique(y)
            onehot = np.zeros((y.size, self.classes_.size), np.float32)
            onehot[np.arange(y.size),
                   np.searchsorted(self.classes_, y)] = 1.0
            y = onehot
        else:
            self.classes_ = np.arange(y.shape[1])
        return self._fit_net(x, y)

    def predict_proba(self, x) -> np.ndarray:
        self._check_fitted()
        out = self.net_.output(np.asarray(x))
        if isinstance(out, (list, tuple)):
            out = out[0]
        return np.asarray(out)

    def predict(self, x) -> np.ndarray:
        proba = self.predict_proba(x)  # raises if unfitted
        return self.classes_[np.argmax(proba, axis=-1)]

    def score(self, x, y) -> float:
        """Mean accuracy (the sklearn classifier contract)."""
        return float(np.mean(self.predict(x) == np.asarray(y)))


class DL4JRegressor(RegressorMixin, DL4JEstimator):
    """Regressor over an identity/linear-output network. y: [N] or [N, K]."""

    def fit(self, x, y) -> "DL4JRegressor":
        x = np.asarray(x)
        y = np.asarray(y, np.float32)
        self._squeeze = y.ndim == 1
        if self._squeeze:
            y = y[:, None]
        return self._fit_net(x, y)

    def predict(self, x) -> np.ndarray:
        self._check_fitted()
        out = self.net_.output(np.asarray(x))
        if isinstance(out, (list, tuple)):
            out = out[0]
        out = np.asarray(out)
        return out[:, 0] if self._squeeze and out.ndim == 2 else out

    def score(self, x, y) -> float:
        """R^2 (the sklearn regressor contract)."""
        y = np.asarray(y, np.float64)
        pred = np.asarray(self.predict(x), np.float64)
        ss_res = float(np.sum((y - pred) ** 2))
        ss_tot = float(np.sum((y - np.mean(y)) ** 2))
        return 1.0 - ss_res / ss_tot if ss_tot else 0.0
