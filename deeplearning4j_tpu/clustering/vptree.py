"""Vantage-point tree for k-NN (reference: deeplearning4j-core
clustering/vptree/VPTree.java:39 — metric-space search used by the
nearest-neighbor server and t-SNE).

Build: recursive random-vantage median partitioning (numpy). Queries: exact
k-NN with triangle-inequality pruning. ``search_batch`` offers the
TPU-friendly alternative: brute-force [Q, N] distance matmul on device —
for the server's batched queries this beats pointer-chasing.
"""

from __future__ import annotations

import heapq
from typing import Optional

import numpy as np


class _VPNode:
    __slots__ = ("index", "threshold", "inside", "outside")

    def __init__(self, index):
        self.index = index
        self.threshold = 0.0
        self.inside: Optional[_VPNode] = None
        self.outside: Optional[_VPNode] = None


def _distances(metric, a, b):
    if metric == "euclidean":
        return np.linalg.norm(b - a, axis=-1)
    if metric == "cosine":
        an = a / max(np.linalg.norm(a), 1e-12)
        bn = b / np.maximum(np.linalg.norm(b, axis=-1, keepdims=True), 1e-12)
        return 1.0 - bn @ an
    raise ValueError(f"Unknown metric '{metric}'")


class VPTree:
    def __init__(self, points, metric: str = "euclidean", seed: int = 0):
        self.points = np.asarray(points, np.float64)
        self.metric = metric
        rng = np.random.default_rng(seed)
        self._root = self._build(np.arange(self.points.shape[0]), rng)

    def _build(self, idx: np.ndarray, rng) -> Optional[_VPNode]:
        if idx.size == 0:
            return None
        vp_pos = rng.integers(idx.size)
        vp = idx[vp_pos]
        rest = np.delete(idx, vp_pos)
        node = _VPNode(int(vp))
        if rest.size == 0:
            return node
        d = _distances(self.metric, self.points[vp], self.points[rest])
        median = float(np.median(d))
        node.threshold = median
        node.inside = self._build(rest[d <= median], rng)
        node.outside = self._build(rest[d > median], rng)
        return node

    def search(self, query, k: int) -> list:
        """[(distance, index)] of the k nearest, ascending (reference:
        VPTree.search)."""
        query = np.asarray(query, np.float64)
        heap: list = []  # max-heap (-d, idx)
        tau = [np.inf]

        def rec(node):
            if node is None:
                return
            d = float(_distances(self.metric, query,
                                 self.points[node.index][None])[0])
            if len(heap) < k:
                heapq.heappush(heap, (-d, node.index))
                if len(heap) == k:
                    tau[0] = -heap[0][0]
            elif d < tau[0]:
                heapq.heapreplace(heap, (-d, node.index))
                tau[0] = -heap[0][0]
            if node.inside is None and node.outside is None:
                return
            if d < node.threshold:
                rec(node.inside)
                if d + tau[0] >= node.threshold:
                    rec(node.outside)
            else:
                rec(node.outside)
                if d - tau[0] <= node.threshold:
                    rec(node.inside)

        rec(self._root)
        return sorted((-d, i) for d, i in heap)

    def search_batch(self, queries, k: int) -> list:
        """Brute-force batched k-NN on device: one [Q, N] distance matrix
        (MXU) + top-k — the TPU path for server-sized batches."""
        import jax.numpy as jnp

        q = jnp.asarray(np.asarray(queries, np.float32))
        p = jnp.asarray(self.points.astype(np.float32))
        if self.metric == "euclidean":
            d2 = (jnp.sum(q * q, 1)[:, None] - 2.0 * q @ p.T
                  + jnp.sum(p * p, 1)[None, :])
            d = jnp.sqrt(jnp.maximum(d2, 0.0))
        else:
            qn = q / jnp.maximum(jnp.linalg.norm(q, axis=1, keepdims=True),
                                 1e-12)
            pn = p / jnp.maximum(jnp.linalg.norm(p, axis=1, keepdims=True),
                                 1e-12)
            d = 1.0 - qn @ pn.T
        import jax

        neg, idx = jax.lax.top_k(-d, k)
        return [list(zip((-np.asarray(neg[i])).tolist(),
                         np.asarray(idx[i]).tolist()))
                for i in range(q.shape[0])]
