"""KD-tree (reference: deeplearning4j-core clustering/kdtree/KDTree.java —
insert + nn/knn/range queries). Host-side numpy structure: spatial search is
pointer-chasing, exactly the workload that does NOT belong on the MXU; the
device-side alternative (brute-force matmul distances) lives in
KMeansClustering/VPTree.batch paths."""

from __future__ import annotations

import heapq
from typing import Optional

import numpy as np


class _Node:
    __slots__ = ("point", "index", "axis", "left", "right")

    def __init__(self, point, index, axis):
        self.point = point
        self.index = index
        self.axis = axis
        self.left: Optional[_Node] = None
        self.right: Optional[_Node] = None


class KDTree:
    def __init__(self, dims: int):
        self.dims = dims
        self._root: Optional[_Node] = None
        self.size = 0

    @staticmethod
    def build(points) -> "KDTree":
        """Balanced build via median splits."""
        pts = np.asarray(points, np.float64)
        tree = KDTree(pts.shape[1])

        def rec(idx, depth):
            if idx.size == 0:
                return None
            axis = depth % tree.dims
            order = idx[np.argsort(pts[idx, axis])]
            mid = order.size // 2
            node = _Node(pts[order[mid]], int(order[mid]), axis)
            node.left = rec(order[:mid], depth + 1)
            node.right = rec(order[mid + 1:], depth + 1)
            return node

        tree._root = rec(np.arange(pts.shape[0]), 0)
        tree.size = pts.shape[0]
        return tree

    def insert(self, point, index: Optional[int] = None) -> None:
        point = np.asarray(point, np.float64)
        idx = self.size if index is None else index
        if self._root is None:
            self._root = _Node(point, idx, 0)
        else:
            node = self._root
            while True:
                axis = node.axis
                branch = "left" if point[axis] < node.point[axis] else "right"
                nxt = getattr(node, branch)
                if nxt is None:
                    setattr(node, branch,
                            _Node(point, idx, (axis + 1) % self.dims))
                    break
                node = nxt
        self.size += 1

    def nn(self, query):
        """(distance, index) of the nearest neighbour."""
        res = self.knn(query, 1)
        return res[0] if res else None

    def knn(self, query, k: int) -> list:
        """[(distance, index)] of k nearest, ascending."""
        query = np.asarray(query, np.float64)
        heap: list = []  # max-heap via negative distance

        def rec(node):
            if node is None:
                return
            d = float(np.linalg.norm(query - node.point))
            if len(heap) < k:
                heapq.heappush(heap, (-d, node.index))
            elif d < -heap[0][0]:
                heapq.heapreplace(heap, (-d, node.index))
            diff = query[node.axis] - node.point[node.axis]
            near, far = (node.left, node.right) if diff < 0 \
                else (node.right, node.left)
            rec(near)
            if len(heap) < k or abs(diff) < -heap[0][0]:
                rec(far)

        rec(self._root)
        return sorted((-d, i) for d, i in heap)

    def range(self, lower, upper) -> list:
        """Indices of points inside the axis-aligned box."""
        lower = np.asarray(lower, np.float64)
        upper = np.asarray(upper, np.float64)
        out: list = []

        def rec(node):
            if node is None:
                return
            if np.all(node.point >= lower) and np.all(node.point <= upper):
                out.append(node.index)
            if node.point[node.axis] >= lower[node.axis]:
                rec(node.left)
            if node.point[node.axis] <= upper[node.axis]:
                rec(node.right)

        rec(self._root)
        return out
