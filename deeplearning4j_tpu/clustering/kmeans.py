"""KMeans clustering (reference: deeplearning4j-core clustering/kmeans/
KMeansClustering.java + the iteration machinery under clustering/algorithm/).

TPU-native: each Lloyd iteration is ONE jitted program — [N, K] distance
matrix on the MXU, argmin assignment, segment-sum centroid update — instead
of the reference's multi-threaded per-point loops.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np


@partial(jax.jit, static_argnames=("k",))
def _lloyd_iteration(x, centers, *, k: int):
    # [N, K] squared distances via (x - c)^2 = x^2 - 2xc + c^2 (MXU matmul)
    x2 = jnp.sum(x * x, axis=1, keepdims=True)
    c2 = jnp.sum(centers * centers, axis=1)
    d2 = x2 - 2.0 * (x @ centers.T) + c2
    assign = jnp.argmin(d2, axis=1)
    one_hot = jax.nn.one_hot(assign, k, dtype=x.dtype)  # [N, K]
    counts = one_hot.sum(axis=0)  # [K]
    sums = one_hot.T @ x          # [K, D]
    new_centers = jnp.where(counts[:, None] > 0,
                            sums / jnp.maximum(counts[:, None], 1.0),
                            centers)
    cost = jnp.sum(jnp.min(d2, axis=1))
    return new_centers, assign, cost


class KMeansClustering:
    """reference: KMeansClustering.setup(k, maxIterations, distanceFn)."""

    def __init__(self, k: int, max_iterations: int = 100, tol: float = 1e-6,
                 seed: int = 0):
        self.k = k
        self.max_iterations = max_iterations
        self.tol = tol
        self.seed = seed
        self.centers: np.ndarray = None
        self.cost: float = float("inf")

    def apply_to(self, points) -> np.ndarray:
        """Cluster; returns per-point assignments (reference: applyTo ->
        ClusterSet)."""
        x = jnp.asarray(np.asarray(points, np.float32))
        n = x.shape[0]
        rng = np.random.default_rng(self.seed)
        init_idx = rng.choice(n, self.k, replace=False)
        centers = x[jnp.asarray(init_idx)]
        prev_cost = jnp.inf
        assign = None
        for _ in range(self.max_iterations):
            centers, assign, cost = _lloyd_iteration(x, centers, k=self.k)
            if abs(float(prev_cost) - float(cost)) < self.tol:
                break
            prev_cost = cost
        self.centers = np.asarray(centers)
        self.cost = float(cost)
        return np.asarray(assign)

    def predict(self, points) -> np.ndarray:
        x = np.asarray(points, np.float32)
        d2 = ((x[:, None, :] - self.centers[None, :, :]) ** 2).sum(-1)
        return d2.argmin(axis=1)
