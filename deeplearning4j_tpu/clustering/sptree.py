"""Space-partitioning tree (SPTree) + 2-D QuadTree.

Reference: clustering/sptree/SpTree.java (generic k-d Barnes-Hut tree:
subDivide :169, computeNonEdgeForces :211, computeEdgeForces :253,
isCorrect :286, depth :306) and clustering/quadtree/QuadTree.java (the 2-D
special case). Host-side numpy: these are pointer trees with
data-dependent shapes — the wrong shape for XLA (the TPU Barnes-Hut path
is the static-shaped grid ladder in plot/barnes_hut.py; this module is
the general clustering structure and the reference-parity BH force
evaluator, useful for host-side verification and small-N exact work).

Node storage is array-based (flat parallel arrays, children as indices)
rather than objects — ~20x less Python overhead on construction than a
node-per-object design.
"""

from __future__ import annotations

import numpy as np


class SPTree:
    """Barnes-Hut space-partitioning tree over points [N, D]."""

    def __init__(self, data, max_depth: int = 64):
        data = np.asarray(data, np.float64)
        if data.ndim != 2:
            raise ValueError("data must be [N, D]")
        self.data = data
        n, d = data.shape
        self.d = d
        self.n_children = 2 ** d
        lo = data.min(axis=0)
        hi = data.max(axis=0)
        center = (lo + hi) / 2
        width = np.maximum((hi - lo) / 2, 1e-10) * (1 + 1e-6)
        # flat node arrays, grown on demand
        cap = max(4 * n // 3 + 16, 32)
        self._center = np.zeros((cap, d))        # cell centers
        self._width = np.zeros((cap, d))         # cell half-widths
        self._com = np.zeros((cap, d))           # center of mass
        self._size = np.zeros(cap, np.int64)     # cumulative point count
        self._child0 = np.full(cap, -1, np.int64)  # first child node id
        self._point = np.full(cap, -1, np.int64)   # leaf's point index
        self._n_nodes = 1
        self._center[0] = center
        self._width[0] = width
        self._max_depth = max_depth
        for i in range(n):
            self._insert(i)

    # ------------------------------------------------------------ building
    def _grow(self):
        cap = self._center.shape[0]
        new = cap * 2
        for name in ("_center", "_width", "_com"):
            arr = getattr(self, name)
            out = np.zeros((new, self.d))
            out[:cap] = arr
            setattr(self, name, out)
        for name, fill in (("_size", 0), ("_child0", -1), ("_point", -1)):
            arr = getattr(self, name)
            out = np.full(new, fill, np.int64)
            out[:cap] = arr
            setattr(self, name, out)

    def _subdivide(self, node):
        while self._n_nodes + self.n_children > self._center.shape[0]:
            self._grow()
        c0 = self._n_nodes
        self._child0[node] = c0
        self._n_nodes += self.n_children
        half = self._width[node] / 2
        for k in range(self.n_children):
            off = np.array([(1 if (k >> j) & 1 else -1)
                            for j in range(self.d)], np.float64)
            self._center[c0 + k] = self._center[node] + off * half
            self._width[c0 + k] = half

    def _child_for(self, node, p):
        k = 0
        for j in range(self.d):
            if p[j] > self._center[node, j]:
                k |= 1 << j
        return self._child0[node] + k

    def _insert(self, i):
        p = self.data[i]
        node, depth = 0, 0
        while True:
            # running center of mass + count (reference: insert updates
            # cumSize and centerOfMass on the path down)
            s = self._size[node]
            self._com[node] = (self._com[node] * s + p) / (s + 1)
            self._size[node] = s + 1
            if self._child0[node] >= 0:            # internal: descend
                node = self._child_for(node, p)
                depth += 1
                continue
            if self._size[node] == 1:              # fresh leaf
                self._point[node] = i
                return
            # occupied leaf: split (duplicates beyond max_depth stack in
            # one leaf — same-point insertion must terminate)
            j = self._point[node]
            if depth >= self._max_depth or \
                    np.allclose(self.data[j], p, atol=1e-12):
                return
            self._subdivide(node)
            self._point[node] = -1
            cj = self._child_for(node, self.data[j])
            self._com[cj] = self.data[j]
            # carry the WHOLE stacked count (a leaf may hold several
            # coincident points): everything counted at this node so far
            # except the point being inserted lives at data[j]
            self._size[cj] = self._size[node] - 1
            self._point[cj] = j
            node = self._child_for(node, p)
            depth += 1

    # ------------------------------------------------------------- queries
    def is_correct(self) -> bool:
        """Every point lies inside its leaf's cell (reference:
        SpTree.isCorrect :286)."""
        ok = True

        def rec(node):
            nonlocal ok
            if self._child0[node] < 0:
                i = self._point[node]
                if i >= 0:
                    inside = np.all(np.abs(self.data[i] - self._center[node])
                                    <= self._width[node] + 1e-9)
                    ok = ok and bool(inside)
            else:
                for k in range(self.n_children):
                    rec(self._child0[node] + k)

        rec(0)
        return ok

    def depth(self) -> int:
        def rec(node):
            if self._child0[node] < 0:
                return 1
            return 1 + max(rec(self._child0[node] + k)
                           for k in range(self.n_children))
        return rec(0)

    @property
    def cum_size(self) -> int:
        return int(self._size[0])

    def compute_non_edge_forces(self, point_index: int, theta: float):
        """Barnes-Hut negative forces for one point: walk the tree, treat
        any cell with width/dist < theta as its center of mass
        (reference: computeNonEdgeForces :211, the t-SNE repulsion with
        the 1/(1+||y_i-y_j||^2) kernel). Returns (neg_force [D], sum_q)."""
        p = self.data[point_index]
        neg = np.zeros(self.d)
        sum_q = 0.0
        stack = [0]
        while stack:
            node = stack.pop()
            cnt = int(self._size[node])
            if cnt == 0:
                continue
            is_leaf = self._child0[node] < 0
            # reference: skip the cell that is exactly this point. With
            # stacked duplicates the leaf holds SEVERAL coincident points
            # under one stored index, and every one of them routes here on
            # insertion — so membership is by COORDINATE, not stored
            # index, and exactly one self-contribution is excluded
            # (q=1 into sum_q, zero force).
            eff = cnt
            if is_leaf and np.allclose(self.data[self._point[node]], p,
                                       atol=1e-12):
                eff = cnt - 1
                if eff == 0:
                    continue
            diff = p - self._com[node]
            dist2 = float(diff @ diff)
            max_w = float(self._width[node].max() * 2)  # full cell width
            if is_leaf or max_w / max(np.sqrt(dist2), 1e-12) < theta:
                q = 1.0 / (1.0 + dist2)
                sum_q += eff * q
                neg += eff * q * q * diff
            else:
                c0 = self._child0[node]
                stack.extend(range(c0, c0 + self.n_children))
        return neg, sum_q


class QuadTree(SPTree):
    """2-D special case (reference: clustering/quadtree/QuadTree.java)."""

    def __init__(self, data, max_depth: int = 64):
        data = np.asarray(data)
        if data.ndim != 2 or data.shape[1] != 2:
            raise ValueError("QuadTree requires [N, 2] data")
        super().__init__(data, max_depth=max_depth)
