"""Clustering + spatial search (reference: deeplearning4j-core clustering/ —
kmeans/, kdtree/, vptree/VPTree.java:39, sptree/SpTree.java, quadtree/)."""

from deeplearning4j_tpu.clustering.kmeans import KMeansClustering
from deeplearning4j_tpu.clustering.kdtree import KDTree
from deeplearning4j_tpu.clustering.sptree import QuadTree, SPTree
from deeplearning4j_tpu.clustering.vptree import VPTree

__all__ = ["KMeansClustering", "KDTree", "VPTree", "SPTree", "QuadTree"]
