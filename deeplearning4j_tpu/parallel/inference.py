"""ParallelInference: multi-device batched inference + coalescing server.

Reference: parallelism/ParallelInference.java:33 — per-device worker threads,
an observable queue, and request coalescing (BatchedInferenceObservable:
small requests are merged into one device batch, each caller gets its slice
back). TPU-native design: the forward pass is one jitted program whose batch
axis is sharded over the mesh; "dispatching to N workers" is a sharding
annotation. The serving surface has two entries:

- ``output(x)`` — synchronous sharded forward (one caller owns the batch).
- ``submit(x) -> Future`` — the BatchedInferenceObservable analogue: an
  async handle whose request is COALESCED with concurrent submissions by a
  background batcher (up to ``max_batch`` rows or a ``max_wait_ms``
  deadline, whichever first), dispatched as ONE padded-bucket program call,
  and sliced back per caller. A bounded in-flight queue decouples host
  batch assembly from device compute: the coalescer assembles and
  dispatches batch t+1 while the completer thread waits on batch t's
  device result — jax's async dispatch makes the overlap real.

Both entries share one jit cache, BUCKETED on the batch dim (padded to the
next power of two, rounded to a worker multiple — optimize/bucketing.py) so
arbitrary request sizes compile O(log max_batch) programs, and LRU-bounded
so a long-lived server cannot grow it without bound.

The serving path is guarded end to end (parallel/resilience.py — the
serving counterpart of optimize/health.py): every ``submit`` passes
admission control (beyond ``max_pending`` in-flight requests, reject with
``ServerOverloaded`` instead of queueing unboundedly) and the circuit
breaker's gate (``CircuitOpen`` fast-fail while dispatches are failing at
rate); a ``deadline_s`` budget travels with the request and expires it in
the coalescer BEFORE padding/dispatch (``DeadlineExceeded`` — a device
program is never wasted on a dead request); dispatch runs under
``RetryPolicy`` backoff for ``TransientDispatchError``. The invariant: an
admitted request's future always resolves — with rows, or with a typed
error — never hangs.
"""

from __future__ import annotations

import queue
import threading
import time
from concurrent.futures import Future
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.metrics.registry import MetricsRegistry
from deeplearning4j_tpu.optimize.bucketing import (BoundedCache, bucket_rows,
                                                   pad_rows)
from deeplearning4j_tpu.parallel.mesh import DATA_AXIS, data_mesh
from deeplearning4j_tpu.parallel.resilience import (AdmissionController,
                                                    ChaosPolicy,
                                                    CircuitBreaker,
                                                    CircuitOpen, Deadline,
                                                    DeadlineExceeded,
                                                    RetryPolicy)
from deeplearning4j_tpu.parallel.runtime import (LoopClosed, LoopCrashed,
                                                 ServingLoop, supervisor)


class _Request:
    """One submitted observable: input rows + the future its slice lands in
    (the reference's InferenceObservable, minus the wait/notify), plus the
    request's deadline (None = unbounded) and its submit instant for the
    e2e latency histogram."""

    __slots__ = ("x", "mask", "n", "future", "deadline", "t0")

    def __init__(self, x, mask, deadline: Optional[Deadline] = None):
        self.x = x
        self.mask = mask
        self.n = x.shape[0]
        self.future: Future = Future()
        self.deadline = deadline
        self.t0 = time.monotonic()

    def signature(self):
        return (self.x.shape[1:], self.mask is not None)


class ParallelInference:
    def __init__(self, net, mesh: Optional[Mesh] = None,
                 workers: Optional[int] = None, *, max_batch: int = 64,
                 max_wait_ms: float = 3.0, inflight: int = 2,
                 max_pending: int = 256,
                 retry: Optional[RetryPolicy] = None,
                 breaker: Optional[CircuitBreaker] = None,
                 chaos: Optional[ChaosPolicy] = None,
                 coalescers: int = 1, max_coalescers: int = 4,
                 registry: Optional[MetricsRegistry] = None,
                 quantize: Optional[str] = None):
        """``max_batch``/``max_wait_ms`` bound the coalescer: a batch is
        dispatched when it reaches ``max_batch`` rows or ``max_wait_ms``
        after its first request, whichever comes first. ``inflight`` bounds
        the dispatch pipeline (assembled-but-unfetched batches).

        ``coalescers`` sets the initial batcher-thread count on the shared
        submit queue and ``max_coalescers`` bounds what
        ``set_coalescer_workers`` (the autoscaler's lever) may grow it to.

        Resilience knobs: ``max_pending`` is the admission high-watermark
        (requests beyond it are rejected with ``ServerOverloaded`` instead
        of queueing); ``retry`` retries ``TransientDispatchError`` with
        backoff (default ``RetryPolicy()``, pass a policy with
        ``max_attempts=1`` to disable); ``breaker`` fast-fails submits
        with ``CircuitOpen`` while dispatches fail at rate (default
        ``CircuitBreaker()``, pass ``breaker=False`` to disable); ``chaos``
        wraps the dispatch callable with a fault injector — test/bench
        only, default off.

        ``quantize="int8"`` serves absmax per-channel int8 weights
        (optimize/quantize.py) with the dequant fused into each matmul;
        the caller's net is untouched — the server quantizes a shallow
        copy. Default ``None`` serves the f32 params bit-exact."""
        if quantize is not None:
            from deeplearning4j_tpu.optimize.quantize import quantize_net
            net = quantize_net(net, quantize)
        self.quantize = quantize
        self.net = net
        self.mesh = mesh if mesh is not None else data_mesh(workers)
        self.workers = self.mesh.devices.size
        self._fwd_cache = BoundedCache()
        self.max_batch = int(max_batch)
        self.max_wait_s = float(max_wait_ms) / 1e3
        self.inflight = max(1, int(inflight))
        self.max_coalescers = max(1, int(max_coalescers))
        self.admission = AdmissionController(max_pending)
        self.retry = retry if retry is not None else RetryPolicy()
        self.breaker = (None if breaker is False
                        else breaker if breaker is not None
                        else CircuitBreaker())
        self._dispatch = (chaos.wrap(self._dispatch_fwd) if chaos is not None
                          else self._dispatch_fwd)
        # serving counters live in the registry (leaf-locked), so stats()
        # and the /metrics scrape never take a serving lock
        self.metrics = registry if registry is not None \
            else MetricsRegistry()
        self._m_dispatches = self.metrics.counter(
            "inference_dispatches_total", "device program calls issued")
        self._m_rejected_circuit = self.metrics.counter(
            "inference_rejected_circuit_total",
            "submits fast-failed by the open breaker")
        self._m_retried = self.metrics.counter(
            "inference_retried_total", "dispatch retry attempts")
        self._m_expired = self.metrics.counter(
            "inference_expired_total", "requests expired before dispatch")
        self._m_completed = self.metrics.counter(
            "inference_completed_total", "futures resolved with rows")
        self._m_failed = self.metrics.counter(
            "inference_failed_total", "futures resolved with a typed error")
        self._m_latency = self.metrics.histogram(
            "inference_latency_ms", "submit-to-resolution latency")
        self._m_batch_rows = self.metrics.histogram(
            "inference_batch_rows", "rows per coalesced dispatch",
            buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256))
        self.metrics.gauge("inference_pending", "requests in flight",
                           fn=lambda: self.admission.pending)
        self.metrics.gauge("inference_accepted", "admission accepts",
                           fn=lambda: self.admission.accepted)
        self.metrics.gauge("inference_rejected", "admission rejects",
                           fn=lambda: self.admission.rejected)
        self.metrics.gauge("inference_breaker_open",
                           "0 closed / 0.5 half-open / 1 open",
                           fn=self._breaker_level)
        self.metrics.gauge("inference_coalescer_workers",
                           "live coalescer threads",
                           fn=lambda: self.coalescer_workers)
        self._drain_cv = threading.Condition()
        self._draining = False
        self._chaos = chaos
        # both worker stacks are hosted on the shared serving runtime
        # (parallel/runtime.py): the coalescer pool and the completer are
        # each one supervised ServingLoop with the uniform
        # NEW/RUNNING/DRAINING/CLOSED lifecycle
        self._coalescer: Optional[ServingLoop] = None
        self._completer: Optional[ServingLoop] = None
        # futures admitted but not yet resolved: the supervisor's
        # on_death contract fails every one of these typed when a loop
        # thread dies, so a crash mid-batch cannot strand a caller
        self._outstanding: set = set()
        self._lock = threading.Lock()
        self._closed = False
        self._coalescer_target = min(self.max_coalescers,
                                     max(1, int(coalescers)))

    def _breaker_level(self) -> float:
        if self.breaker is None:
            return 0.0
        return {"closed": 0.0, "half_open": 0.5,
                "open": 1.0}.get(self.breaker.state, 0.0)

    @property
    def dispatch_count(self) -> int:
        """Device program calls issued (coalescing efficiency metric: N
        submits completing in 1 dispatch is the point of the batcher)."""
        return int(self._m_dispatches.value)

    # ----------------------------------------------------------- jit cache
    def _get_fwd(self, shape, has_mask):
        """Compiled sharded forward for one (bucket shape, mask) pair.

        The program lives in the *net's* bucketed output cache, not this
        instance, so every ``ParallelInference`` over the same net — and
        in particular a supervised fleet restart that rebuilds the replica
        from the same net — reuses the already-compiled programs instead
        of paying a cold recompile on its first request."""

        def build():
            net = self.net
            batch_sharding = NamedSharding(self.mesh, P(DATA_AXIS))
            replicated = NamedSharding(self.mesh, P())

            if hasattr(net, "layers") and isinstance(net.layers, list):
                def fwd(params, state, x, mask):
                    out, _, _, _ = net._forward(params, state, x, mask,
                                                train=False, rng=None)
                    return out
            else:  # ComputationGraph, single input/output
                def fwd(params, state, x, mask):
                    outs, _, _, _, _ = net._forward(params, state, [x],
                                                    [mask], train=False,
                                                    rng=None)
                    return outs[0]

            return jax.jit(
                fwd,
                in_shardings=(replicated, replicated, batch_sharding,
                              batch_sharding if has_mask else None),
                out_shardings=batch_sharding)

        if hasattr(self.net, "_get_output"):
            devs = tuple(d.id for d in self.mesh.devices.flat)
            return self.net._get_output(("pi_fwd", shape, has_mask, devs),
                                        build)
        key = (shape, has_mask)  # net without a bucketed cache: keep local
        if key not in self._fwd_cache:
            self._fwd_cache[key] = build()
        return self._fwd_cache[key]

    def _dispatch_fwd(self, x, mask):
        """Pad to the bucket, dispatch the sharded forward (async), return
        the un-fetched device result. The caller strips the padding."""
        n = x.shape[0]
        B = bucket_rows(n, multiple=self.workers)
        if B != n:
            x = pad_rows(x, B)
            if mask is not None:
                mask = pad_rows(np.asarray(mask), B)
        fwd = self._get_fwd(x.shape, mask is not None)
        out = fwd(self.net.params, self.net.state, jnp.asarray(x),
                  jnp.asarray(mask) if mask is not None else None)
        self._m_dispatches.inc()
        return out

    # ---------------------------------------------------------- sync entry
    def output(self, x, mask=None):
        """Sharded forward over the mesh; the batch is padded to the bucket
        size (power of two, worker multiple) and the padding stripped from
        the result."""
        x = np.asarray(x)
        out = self._dispatch_fwd(x, mask)
        return np.asarray(out)[:x.shape[0]]

    # --------------------------------------------------------- async entry
    def submit(self, x, mask=None, *,
               deadline_s: Optional[float] = None) -> Future:
        """Async inference: returns a Future of this request's output rows.
        Requests submitted concurrently are coalesced into one device batch
        (the reference's BatchedInferenceObservable); each future resolves
        to exactly its own rows, in row order.

        ``deadline_s`` is the request's time budget from this call: a
        request still undispatched when it expires fails with
        ``DeadlineExceeded`` (checked in the coalescer BEFORE padding and
        dispatch, so no device program is spent on it). Raises
        ``ServerOverloaded`` when ``max_pending`` requests are in flight
        and ``CircuitOpen`` while the breaker is open — both immediately,
        never by blocking the caller."""
        with self._lock:
            if self._closed or self._draining:
                raise RuntimeError("ParallelInference is closed"
                                   if self._closed else
                                   "ParallelInference is draining")
            co = self._ensure_workers()
        if self.breaker is not None and not self.breaker.allow():
            self._m_rejected_circuit.inc()
            raise CircuitOpen("circuit breaker is open: recent dispatches "
                              "failed above threshold")
        self.admission.acquire()  # raises ServerOverloaded at watermark
        x = np.asarray(x)
        if x.ndim < 2:
            x = x[None]  # single example -> 1-row batch
        req = _Request(x, None if mask is None else np.asarray(mask),
                       None if deadline_s is None else Deadline(deadline_s))
        # the done-callback is the single release point for admission and
        # the completion counters: it fires on EVERY resolution path
        # (result, typed failure, shutdown drain), so pending can never
        # leak no matter which thread resolves the future
        req.future.add_done_callback(
            lambda f, t0=req.t0: self._on_done(f, t0))
        with self._lock:
            self._outstanding.add(req.future)
        try:
            co.put(req)
        except LoopClosed:
            # close() (or a loop crash) raced this submit past the checks
            # above: fail the future rather than hang the caller. _fail
            # tolerates the other side of the race having resolved it.
            with self._lock:
                closed = self._closed
            self._fail(req.future,
                       RuntimeError("ParallelInference is closed") if closed
                       else LoopCrashed("pi-coalescer is restarting; "
                                        "resubmit the request"))
            return req.future
        with self._lock:
            closed = self._closed
        if closed and not req.future.done():
            # the put itself raced close() in: the runtime's leftover
            # drain (re-run by put()) normally fails it, but cover the
            # window where the drain ran before our enqueue landed
            self._fail(req.future,
                       RuntimeError("ParallelInference is closed"))
        return req.future

    def _on_done(self, fut: Future, t0: Optional[float] = None) -> None:
        with self._lock:
            self._outstanding.discard(fut)
        self.admission.release()
        if fut.exception() is None:
            self._m_completed.inc()
            if t0 is not None:
                self._m_latency.observe((time.monotonic() - t0) * 1e3)
        else:
            self._m_failed.inc()
        with self._drain_cv:
            self._drain_cv.notify_all()

    def stats(self) -> dict:
        """Serving counters (monotone except pending/breaker_state): the
        observable surface the UI, bench, and ops read. The snapshot is
        assembled entirely OUTSIDE the serving locks — every counter is a
        leaf-locked registry metric (fleet.py's enforced pattern)."""
        out = {"retried": int(self._m_retried.value),
               "expired": int(self._m_expired.value),
               "rejected_circuit": int(self._m_rejected_circuit.value),
               "completed": int(self._m_completed.value),
               "failed": int(self._m_failed.value),
               "dispatches": int(self._m_dispatches.value)}
        out.update(
            accepted=self.admission.accepted,
            rejected=self.admission.rejected,
            pending=self.admission.pending,
            breaker_state=(self.breaker.state if self.breaker is not None
                           else "disabled"))
        return out

    @staticmethod
    def _fail(future: Future, exc: Exception) -> None:
        """set_exception tolerating an already-resolved future (the
        completer and a closing drain can race on shutdown)."""
        try:
            future.set_exception(exc)
        except Exception:  # noqa: BLE001 — already resolved, either way
            pass

    def _ensure_workers(self) -> ServingLoop:
        """Start the runtime loops once and return the coalescer loop.
        Caller must hold ``self._lock``. Both loop refs are published to
        the instance BEFORE any request can reach a worker (the first
        put happens after this returns), so ``_coalesce_entry`` can
        snapshot them safely."""
        if self._coalescer is None:
            # bounded completer inbox: backpressures the coalescers when
            # `inflight` batches are dispatched but not yet fetched
            completer = ServingLoop(
                "pi-completer", handler=self._complete_loop,
                inbox_maxsize=self.inflight,
                on_leftover=self._fail_inflight_leftover,
                chaos=self._chaos)
            coalescer = ServingLoop(
                "pi-coalescer", handler=self._coalesce_entry,
                workers=self._coalescer_target,
                max_workers=self.max_coalescers,
                on_leftover=self._fail_submit_leftover,
                chaos=self._chaos)
            self._completer = completer
            self._coalescer = coalescer
            completer.start()
            coalescer.start()
            sup = supervisor()
            sup.watch(completer, on_death=self._on_loop_death, restart=True)
            sup.watch(coalescer, on_death=self._on_loop_death, restart=True)
        return self._coalescer

    def _on_loop_death(self, loop: ServingLoop, exc: BaseException):
        """Uniform recovery contract (LoopSupervisor): every admitted but
        unresolved future fails typed — a dead loop thread never strands
        a caller — and the supervised restart proceeds unless the server
        is deliberately closing."""
        with self._lock:
            victims = list(self._outstanding)
            closed = self._closed
        err = LoopCrashed(f"{loop.name} died with the request in flight: "
                          f"{exc!r}")
        for f in victims:
            if not f.done():
                self._fail(f, err)
        return not closed

    def _fail_submit_leftover(self, req) -> None:
        self._fail(req.future, RuntimeError("ParallelInference is closed"))

    def _fail_inflight_leftover(self, item) -> None:
        _out, batch = item
        for r in batch:
            self._fail(r.future, RuntimeError("ParallelInference is closed"))

    @property
    def coalescer_workers(self) -> int:
        """Desired coalescer-thread count (the autoscaler's observable)."""
        with self._lock:
            return self._coalescer_target

    def set_coalescer_workers(self, n: int) -> int:
        """Scale the coalescer pool to ``n`` threads (clamped to
        [1, max_coalescers]). Scale-up spawns threads on the shared
        inbox; scale-down retires workers via the runtime's resign
        tokens, so a coalescer finishes its current batch and exits
        cleanly. The target never drops below 1, so the shutdown
        sentinel always finds a live coalescer to propagate through."""
        n = min(self.max_coalescers, max(1, int(n)))
        with self._lock:
            if self._closed:
                return self._coalescer_target
            self._coalescer_target = n
            co = self._coalescer
        if co is not None:
            co.set_workers(n)
        return n

    def _expire_if_dead(self, req) -> bool:
        """Fail an already-expired request with DeadlineExceeded (True),
        or report it still live (False). Every coalescer touchpoint runs
        this BEFORE spending work on the request."""
        if req.deadline is None or not req.deadline.expired():
            return False
        self._m_expired.inc()
        self._fail(req.future, DeadlineExceeded(
            f"request expired {-req.deadline.remaining() * 1e3:.1f} ms "
            "before dispatch"))
        return True

    @staticmethod
    def _flush_by(d) -> float:
        """Latest instant the assembly window may run to for a member with
        deadline ``d``: a quarter of the member's remaining budget is
        reserved for the dispatch itself, so flushing at the window edge
        still lands BEFORE expiry instead of exactly on it."""
        return d.expires_at - 0.25 * max(0.0, d.remaining())

    def _coalesce_entry(self, first):
        """Coalescer loop handler. Snapshots the loop refs under the
        lock, then assembles and dispatches entirely outside it — the
        retry backoff and queue waits in the batch path must never run
        under ``self._lock``."""
        with self._lock:
            co, completer = self._coalescer, self._completer
        return self._coalesce_once(first, co, completer)

    def _coalesce_once(self, first, co: ServingLoop, completer: ServingLoop):
        """Coalescer handler: assemble ONE batch starting from ``first``
        and dispatch it. Returns the mismatched request that forced an
        early flush (the runtime hands it back as this worker's next
        head), or None. Sentinel/resign/pool-walk choreography lives in
        the runtime, not here."""
        if self._expire_if_dead(first):
            return None
        head = None
        batch = [first]
        rows = first.n
        sig = first.signature()
        deadline = time.monotonic() + self.max_wait_s
        if first.deadline is not None:
            # remaining-time propagation: a member with less budget
            # than the coalesce window flushes the batch early, so it
            # is dispatched before it expires rather than after
            deadline = min(deadline, self._flush_by(first.deadline))
        while rows < self.max_batch:
            wait = deadline - time.monotonic()
            if wait <= 0:
                break
            try:
                # loop.get never hands out control tokens: a shutdown
                # sentinel arriving mid-assembly re-queues and raises
                # Empty, so the batch flushes and the main consume loop
                # runs the pool walk
                nxt = co.get(timeout=wait)
            except queue.Empty:
                break
            if nxt.signature() != sig:
                head = nxt  # flush now; the mismatch starts its own batch
                break
            if self._expire_if_dead(nxt):
                continue
            batch.append(nxt)
            rows += nxt.n
            if nxt.deadline is not None:
                deadline = min(deadline, self._flush_by(nxt.deadline))
        self._dispatch_batch(batch, completer)
        return head

    def _count_retry(self, attempt, exc) -> None:
        self._m_retried.inc()

    def _dispatch_batch(self, batch, completer: ServingLoop):
        # last expiry gate: members that died waiting in the assembly
        # window fail typed here, before any padding or device work
        batch = [r for r in batch if not self._expire_if_dead(r)]
        if not batch:
            return
        self._m_batch_rows.observe(sum(r.n for r in batch))
        earliest = min((r.deadline for r in batch if r.deadline is not None),
                       key=lambda d: d.expires_at, default=None)

        def attempt():
            try:
                out = self._dispatch(x, mask)  # async dispatch, no fetch
            except Exception:
                if self.breaker is not None:
                    self.breaker.record_failure()
                raise
            if self.breaker is not None:
                self.breaker.record_success()
            return out

        try:
            x = (batch[0].x if len(batch) == 1
                 else np.concatenate([r.x for r in batch]))
            mask = None
            if batch[0].mask is not None:
                mask = (batch[0].mask if len(batch) == 1
                        else np.concatenate([r.mask for r in batch]))
            out = self.retry.call(attempt, deadline=earliest,
                                  on_retry=self._count_retry)
        except Exception as e:  # noqa: BLE001 — surface on every future
            for r in batch:
                # a member whose budget died during the retry storm fails
                # as DeadlineExceeded; the rest carry the original error
                if not self._expire_if_dead(r):
                    self._fail(r.future, e)
            return
        # bounded pipeline: blocks when `inflight` batches are already
        # pending, so device compute overlaps the NEXT batch's host
        # assembly. The put is chunked so a dead completer cannot wedge
        # this coalescer forever: each timeout re-checks the completer's
        # health and fails the batch typed instead of stranding it.
        while True:
            if completer.crashed is not None:
                err = LoopCrashed("pi-completer died with the batch in "
                                  "flight")
                for r in batch:
                    self._fail(r.future, err)
                return
            try:
                completer.put((out, batch), timeout=0.2)
                return
            except queue.Full:
                continue
            except LoopClosed:
                err = RuntimeError("ParallelInference is closed")
                for r in batch:
                    self._fail(r.future, err)
                return

    def _complete_loop(self, item):
        """Completer handler: THE single device fetch per coalesced
        batch, sliced back per caller. Hosted on its own ServingLoop so
        the fetch overlaps the coalescers' next assembly."""
        out, batch = item
        try:
            arr = np.asarray(out)  # the device fetch for this batch
        except Exception as e:  # noqa: BLE001
            for r in batch:
                self._fail(r.future, e)
            return None
        ofs = 0
        for r in batch:
            try:
                r.future.set_result(arr[ofs:ofs + r.n])
            except Exception:  # noqa: BLE001 — lost a shutdown race
                pass
            ofs += r.n
        return None

    # ------------------------------------------------------------ lifecycle
    def drain(self, timeout: Optional[float] = None) -> bool:
        """Graceful drain: stop admitting new submits (they raise
        RuntimeError) while every in-flight request runs to resolution.
        Returns True once nothing is pending, False if ``timeout`` seconds
        pass first (in-flight work keeps completing either way). The first
        phase of ``close()``; also usable alone for zero-loss handoff
        (drain, swap weights/process, resume)."""
        with self._lock:
            self._draining = True
            co, cm = self._coalescer, self._completer
        # advance the runtime state machines too, so shutdown-phase chaos
        # (kill_during_drain) fires on work handled from here on
        if co is not None:
            co.begin_drain()
        if cm is not None:
            cm.begin_drain()
        limit = None if timeout is None else time.monotonic() + timeout
        while True:
            # liveness read OUTSIDE _drain_cv: ServingLoop._cond ranks
            # below the drain condition, so it may never be acquired
            # while the cv is held
            dead = co is None or (co.alive_workers == 0
                                  and (cm is None
                                       or cm.alive_workers == 0))
            with self._drain_cv:
                if self.admission.pending == 0:
                    return True
                if dead:
                    # no loop worker will ever resolve the remainder
                    # (crashed loops, or staged shutdown tests): close()'s
                    # leftover drain owns those requests
                    return False
                wait = 0.2 if limit is None else min(
                    0.2, limit - time.monotonic())
                if wait <= 0:
                    return False
                self._drain_cv.wait(wait)  # chunked: re-checks liveness

    def close(self, timeout: float = 30.0):
        """Drain (complete in-flight work, reject new submissions), then
        flush and stop both runtime loops. Idempotent and re-entrant
        (any thread, twice, concurrently): the runtime's sole-closer
        discipline makes late callers wait on the first closer's
        completion event. Pending futures complete before the loops
        exit; requests that raced the shutdown in behind the sentinel
        are FAILED with RuntimeError, never left unresolved."""
        with self._lock:
            should_drain = not self._closed and self._coalescer is not None
        if should_drain:
            self.drain(timeout)
        with self._lock:
            self._closed = True
            co, cm = self._coalescer, self._completer
        if co is None:
            return
        co.close(timeout)
        cm.close(timeout)
        # a submit that raced close() past the runtime's own leftover
        # drain may have re-queued behind the sentinel: run the
        # idempotent drain once more
        co.fail_leftovers()
        # a stalled/killed worker can leave popped-but-unresolved
        # requests behind (stall_sentinel chaos): fail whatever is still
        # outstanding so no caller ever hangs on result()
        with self._lock:
            victims = [f for f in self._outstanding if not f.done()]
        for f in victims:
            self._fail(f, RuntimeError("ParallelInference is closed"))

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
