"""ParallelInference: multi-device batched inference.

Reference: parallelism/ParallelInference.java:33 — per-device worker threads,
an observable queue, and optional request coalescing (BatchedInferenceObservable)
to batch small requests before dispatch. TPU-native design: the forward pass is
one jitted program whose batch axis is sharded over the mesh; "dispatching to N
workers" is a sharding annotation, and request coalescing maps to host-side
batching with padding to a multiple of the device count.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.parallel.mesh import DATA_AXIS, data_mesh


class ParallelInference:
    def __init__(self, net, mesh: Optional[Mesh] = None,
                 workers: Optional[int] = None):
        self.net = net
        self.mesh = mesh if mesh is not None else data_mesh(workers)
        self.workers = self.mesh.devices.size
        self._fwd_cache: dict = {}

    def _get_fwd(self, shape, has_mask):
        key = (shape, has_mask)
        if key not in self._fwd_cache:
            net = self.net
            batch_sharding = NamedSharding(self.mesh, P(DATA_AXIS))
            replicated = NamedSharding(self.mesh, P())

            def fwd(params, state, x, mask):
                out, _, _, _ = net._forward(params, state, x, mask, train=False,
                                            rng=None)
                return out

            self._fwd_cache[key] = jax.jit(
                fwd,
                in_shardings=(replicated, replicated, batch_sharding,
                              batch_sharding if has_mask else None),
                out_shardings=batch_sharding)
        return self._fwd_cache[key]

    def output(self, x, mask=None):
        """Sharded forward over the mesh; batch is padded to a multiple of the
        worker count and the padding stripped from the result (the reference's
        batched-observable coalescing, minus the threads)."""
        x = np.asarray(x)
        n = x.shape[0]
        W = self.workers
        pad = (-n) % W
        if pad:
            x = np.concatenate([x, np.repeat(x[-1:], pad, axis=0)], axis=0)
            if mask is not None:
                mask = np.concatenate(
                    [np.asarray(mask), np.repeat(np.asarray(mask)[-1:], pad,
                                                 axis=0)], axis=0)
        fwd = self._get_fwd(x.shape, mask is not None)
        out = fwd(self.net.params, self.net.state, jnp.asarray(x),
                  jnp.asarray(mask) if mask is not None else None)
        return np.asarray(out)[:n]
