"""ParallelInference: multi-device batched inference + coalescing server.

Reference: parallelism/ParallelInference.java:33 — per-device worker threads,
an observable queue, and request coalescing (BatchedInferenceObservable:
small requests are merged into one device batch, each caller gets its slice
back). TPU-native design: the forward pass is one jitted program whose batch
axis is sharded over the mesh; "dispatching to N workers" is a sharding
annotation. The serving surface has two entries:

- ``output(x)`` — synchronous sharded forward (one caller owns the batch).
- ``submit(x) -> Future`` — the BatchedInferenceObservable analogue: an
  async handle whose request is COALESCED with concurrent submissions by a
  background batcher (up to ``max_batch`` rows or a ``max_wait_ms``
  deadline, whichever first), dispatched as ONE padded-bucket program call,
  and sliced back per caller. A bounded in-flight queue decouples host
  batch assembly from device compute: the coalescer assembles and
  dispatches batch t+1 while the completer thread waits on batch t's
  device result — jax's async dispatch makes the overlap real.

Both entries share one jit cache, BUCKETED on the batch dim (padded to the
next power of two, rounded to a worker multiple — optimize/bucketing.py) so
arbitrary request sizes compile O(log max_batch) programs, and LRU-bounded
so a long-lived server cannot grow it without bound.
"""

from __future__ import annotations

import queue
import threading
from concurrent.futures import Future
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.optimize.bucketing import (BoundedCache, bucket_rows,
                                                   pad_rows)
from deeplearning4j_tpu.parallel.mesh import DATA_AXIS, data_mesh

_SHUTDOWN = object()


class _Request:
    """One submitted observable: input rows + the future its slice lands in
    (the reference's InferenceObservable, minus the wait/notify)."""

    __slots__ = ("x", "mask", "n", "future")

    def __init__(self, x, mask):
        self.x = x
        self.mask = mask
        self.n = x.shape[0]
        self.future: Future = Future()

    def signature(self):
        return (self.x.shape[1:], self.mask is not None)


class ParallelInference:
    def __init__(self, net, mesh: Optional[Mesh] = None,
                 workers: Optional[int] = None, *, max_batch: int = 64,
                 max_wait_ms: float = 3.0, inflight: int = 2):
        """``max_batch``/``max_wait_ms`` bound the coalescer: a batch is
        dispatched when it reaches ``max_batch`` rows or ``max_wait_ms``
        after its first request, whichever comes first. ``inflight`` bounds
        the dispatch pipeline (assembled-but-unfetched batches)."""
        self.net = net
        self.mesh = mesh if mesh is not None else data_mesh(workers)
        self.workers = self.mesh.devices.size
        self._fwd_cache = BoundedCache()
        self.max_batch = int(max_batch)
        self.max_wait_s = float(max_wait_ms) / 1e3
        self.inflight = max(1, int(inflight))
        #: device program calls issued (coalescing efficiency metric: N
        #: submits completing in 1 dispatch is the point of the batcher)
        self.dispatch_count = 0
        self._submit_q: Optional[queue.Queue] = None
        self._inflight_q: Optional[queue.Queue] = None
        self._threads: list = []
        self._lock = threading.Lock()
        self._closed = False

    # ----------------------------------------------------------- jit cache
    def _get_fwd(self, shape, has_mask):
        key = (shape, has_mask)
        if key not in self._fwd_cache:
            net = self.net
            batch_sharding = NamedSharding(self.mesh, P(DATA_AXIS))
            replicated = NamedSharding(self.mesh, P())

            if hasattr(net, "layers") and isinstance(net.layers, list):
                def fwd(params, state, x, mask):
                    out, _, _, _ = net._forward(params, state, x, mask,
                                                train=False, rng=None)
                    return out
            else:  # ComputationGraph, single input/output
                def fwd(params, state, x, mask):
                    outs, _, _, _, _ = net._forward(params, state, [x],
                                                    [mask], train=False,
                                                    rng=None)
                    return outs[0]

            self._fwd_cache[key] = jax.jit(
                fwd,
                in_shardings=(replicated, replicated, batch_sharding,
                              batch_sharding if has_mask else None),
                out_shardings=batch_sharding)
        return self._fwd_cache[key]

    def _dispatch_fwd(self, x, mask):
        """Pad to the bucket, dispatch the sharded forward (async), return
        the un-fetched device result. The caller strips the padding."""
        n = x.shape[0]
        B = bucket_rows(n, multiple=self.workers)
        if B != n:
            x = pad_rows(x, B)
            if mask is not None:
                mask = pad_rows(np.asarray(mask), B)
        fwd = self._get_fwd(x.shape, mask is not None)
        out = fwd(self.net.params, self.net.state, jnp.asarray(x),
                  jnp.asarray(mask) if mask is not None else None)
        self.dispatch_count += 1
        return out

    # ---------------------------------------------------------- sync entry
    def output(self, x, mask=None):
        """Sharded forward over the mesh; the batch is padded to the bucket
        size (power of two, worker multiple) and the padding stripped from
        the result."""
        x = np.asarray(x)
        out = self._dispatch_fwd(x, mask)
        return np.asarray(out)[:x.shape[0]]

    # --------------------------------------------------------- async entry
    def submit(self, x, mask=None) -> Future:
        """Async inference: returns a Future of this request's output rows.
        Requests submitted concurrently are coalesced into one device batch
        (the reference's BatchedInferenceObservable); each future resolves
        to exactly its own rows, in row order."""
        if self._closed:
            raise RuntimeError("ParallelInference is closed")
        x = np.asarray(x)
        if x.ndim < 2:
            x = x[None]  # single example -> 1-row batch
        req = _Request(x, None if mask is None else np.asarray(mask))
        self._ensure_workers()
        self._submit_q.put(req)
        if self._closed and not req.future.done():
            # close() raced this submit past the _closed check above: the
            # request may sit behind the shutdown sentinel (or behind
            # close()'s queue drain) where no thread will ever serve it —
            # fail it rather than hang the caller. _fail tolerates the
            # other side of the race having resolved it first.
            self._fail(req.future,
                       RuntimeError("ParallelInference is closed"))
        return req.future

    @staticmethod
    def _fail(future: Future, exc: Exception) -> None:
        """set_exception tolerating an already-resolved future (the
        completer and a closing drain can race on shutdown)."""
        try:
            future.set_exception(exc)
        except Exception:  # noqa: BLE001 — already resolved, either way
            pass

    def _ensure_workers(self):
        if self._threads:
            return
        with self._lock:
            if self._threads:
                return
            self._submit_q = queue.Queue()
            # bounded: backpressures the coalescer when `inflight` batches
            # are dispatched but not yet fetched
            self._inflight_q = queue.Queue(maxsize=self.inflight)
            coalescer = threading.Thread(target=self._coalesce_loop,
                                         name="pi-coalescer", daemon=True)
            completer = threading.Thread(target=self._complete_loop,
                                         name="pi-completer", daemon=True)
            self._threads = [coalescer, completer]
            coalescer.start()
            completer.start()

    def _coalesce_loop(self):
        import time

        q = self._submit_q
        head = None
        while True:
            first = head if head is not None else q.get()
            head = None
            if first is _SHUTDOWN:
                self._inflight_q.put(_SHUTDOWN)
                return
            batch = [first]
            rows = first.n
            sig = first.signature()
            deadline = time.monotonic() + self.max_wait_s
            while rows < self.max_batch:
                wait = deadline - time.monotonic()
                if wait <= 0:
                    break
                try:
                    nxt = q.get(timeout=wait)
                except queue.Empty:
                    break
                if nxt is _SHUTDOWN or nxt.signature() != sig:
                    head = nxt  # flush now; the mismatch starts its own batch
                    break
                batch.append(nxt)
                rows += nxt.n
            self._dispatch_batch(batch)

    def _dispatch_batch(self, batch):
        try:
            x = (batch[0].x if len(batch) == 1
                 else np.concatenate([r.x for r in batch]))
            mask = None
            if batch[0].mask is not None:
                mask = (batch[0].mask if len(batch) == 1
                        else np.concatenate([r.mask for r in batch]))
            out = self._dispatch_fwd(x, mask)  # async dispatch, no fetch
        except Exception as e:  # noqa: BLE001 — surface on every future
            for r in batch:
                self._fail(r.future, e)
            return
        # blocks when `inflight` batches are already pending — bounded
        # pipeline: device compute overlaps the NEXT batch's host assembly
        self._inflight_q.put((out, batch))

    def _complete_loop(self):
        while True:
            item = self._inflight_q.get()
            if item is _SHUTDOWN:
                return
            out, batch = item
            try:
                arr = np.asarray(out)  # the device fetch for this batch
            except Exception as e:  # noqa: BLE001
                for r in batch:
                    self._fail(r.future, e)
                continue
            ofs = 0
            for r in batch:
                try:
                    r.future.set_result(arr[ofs:ofs + r.n])
                except Exception:  # noqa: BLE001 — lost a shutdown race
                    pass
                ofs += r.n

    # ------------------------------------------------------------ lifecycle
    def close(self):
        """Flush and stop the coalescer threads (idempotent). Pending
        futures complete before the threads exit; requests that raced the
        shutdown in behind the sentinel are FAILED with RuntimeError,
        never left unresolved."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            threads, self._threads = self._threads, []
            submit_q = self._submit_q
        if threads:
            submit_q.put(_SHUTDOWN)
            for t in threads:
                t.join(timeout=30)
        if submit_q is None:
            return
        # drain anything a racing submit() slipped in behind the sentinel —
        # the coalescer exited at the sentinel, so these would otherwise
        # hold unresolved futures forever
        while True:
            try:
                req = submit_q.get_nowait()
            except queue.Empty:
                break
            if req is not _SHUTDOWN:
                self._fail(req.future,
                           RuntimeError("ParallelInference is closed"))

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
