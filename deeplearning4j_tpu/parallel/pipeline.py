"""Pipeline parallelism: GPipe-style microbatch training over layer stages.

Beyond reference parity (SURVEY §2.4 checklist: "PP: absent" in DL4J; the
charter lists PP as an idiomatic TPU extension alongside TP/SP). Design:
the network's layers are split into contiguous STAGES, each stage's
parameters live on their own device, and a minibatch is fed through as M
microbatches. Three deliberate choices:

- **Host-scheduled, per-stage jitted programs** (not one SPMD program over
  a 'pipe' mesh axis): stacked-stage SPMD pipelining requires homogeneous
  stages; real DL4J-style networks are heterogeneous (conv stem -> dense
  head), so each stage compiles its own program and JAX's async dispatch
  provides the overlap — the host enqueues the whole forward schedule
  without blocking, and microbatch m's stage-s program runs on device s
  while m+1's stage-(s-1) program runs on device s-1. Device-to-device
  activation transfers ride ICI.
- **Recompute backward** (activation rematerialisation, the GPipe paper's
  memory trick): the backward program for a stage recomputes its forward
  from the stashed stage INPUT inside ``jax.vjp``, so only per-stage
  inputs — not internals — are kept, O(M) small stashes per stage.
- **Exact parity contract**: with equal-size microbatches, summing
  microbatch gradients of per-microbatch-mean losses divided by M equals
  the full-batch mean-loss gradient, so pipeline training matches
  single-device training up to float order (tested).

Scope: feed-forward stacks (Dense/Conv/pooling/BN/...). Recurrent carry
and masks stay with TBPTT/ring-attention paths. Layer state (e.g. BN
running stats) is updated from the last microbatch per step.
"""

from __future__ import annotations

from functools import partial
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np


def balanced_stages(net, n_stages: int) -> List[List[int]]:
    """Split layer indices into contiguous stages balanced by parameter
    count (the pipeline's load balance is set by its slowest stage)."""
    sizes = [sum(int(np.asarray(p).size) for p in net.params[str(i)].values())
             + 1 for i in range(len(net.layers))]
    total = sum(sizes)
    target = total / n_stages
    stages, cur, acc = [], [], 0.0
    for i, s in enumerate(sizes):
        cur.append(i)
        acc += s
        remaining_layers = len(sizes) - i - 1
        remaining_stages = n_stages - len(stages) - 1
        if (acc >= target and remaining_stages > 0) or \
                remaining_layers == remaining_stages > 0:
            stages.append(cur)
            cur, acc = [], 0.0
    if cur:
        stages.append(cur)
    return stages


class PipelineTrainer:
    """Train a MultiLayerNetwork over ``n_stages`` devices with ``n_micro``
    microbatches per step (reference analog: none — DL4J has no PP)."""

    def __init__(self, net, n_stages: int = 2, n_micro: int = 4,
                 devices: Optional[list] = None):
        if devices is None:
            devices = jax.devices()[:n_stages]
        if len(devices) < n_stages:
            raise ValueError(f"need {n_stages} devices, have {len(devices)}")
        self.net = net
        self.n_micro = n_micro
        self.devices = devices[:n_stages]
        self.stages = balanced_stages(net, n_stages)
        conf = net.conf
        self.updater = conf.updater
        # place each stage's params/state/updater-state on its device
        self._params = []
        self._states = []
        self._opt = []
        for s, idxs in enumerate(self.stages):
            p = {str(i): net.params[str(i)] for i in idxs}
            st = {str(i): net.state.get(str(i), {}) for i in idxs}
            p = jax.device_put(p, self.devices[s])
            st = jax.device_put(st, self.devices[s])
            self._params.append(p)
            self._states.append(st)
            self._opt.append(jax.device_put(self.updater.init(p),
                                            self.devices[s]))
        self._fwd = [self._make_fwd(s) for s in range(len(self.stages))]
        self._bwd = [self._make_bwd(s) for s in range(len(self.stages))]
        self._upd = [self._make_update(s) for s in range(len(self.stages))]
        self.iteration = 0
        self.score_value = float("nan")

    # ------------------------------------------------------------ programs
    def _apply_layers(self, idxs, params, state, x, rng):
        """The ONE stage-body forward shared by fwd, loss, and the
        recompute backward: preprocessors + layer.forward over ``idxs``,
        rng split per layer exactly once (so the backward's recompute sees
        the identical dropout masks as the forward)."""
        conf = self.net.conf
        from deeplearning4j_tpu.nn.conf.preprocessors import preprocessor_key
        new_state = {}
        keys = (jax.random.split(rng, max(len(idxs), 1))
                if rng is not None else [None] * len(idxs))
        for k, i in zip(keys, idxs):
            layer = self.net.layers[i]
            if i in conf.preprocessors:
                x = conf.preprocessors[i].forward(
                    x, rng=preprocessor_key(k) if k is not None else None)
            x, ns = layer.forward(params[str(i)], state.get(str(i), {}), x,
                                  train=True, rng=k)
            new_state[str(i)] = ns
        return x, new_state

    def _stage_reg(self, s, params):
        """This stage's share of the L1/L2 term MultiLayerNetwork._loss
        adds (regularization is a per-layer sum, so it localizes to
        stages exactly). Value only — gradients use the closed form below
        (same split as nn/regularization.py)."""
        reg = 0.0
        for i in self.stages[s]:
            reg = reg + self.net.layers[i].regularization(params[str(i)])
        if not isinstance(reg, float):
            reg = jax.lax.stop_gradient(reg)
        return reg

    def _add_stage_reg_grads(self, s, params, dp):
        """Closed-form l1/l2 gradients for this stage's layers, added into
        the stage gradient tree (the pipeline analog of
        nn.regularization.add_regularization_grads)."""
        for i in self.stages[s]:
            sub = params.get(str(i), {})
            for k, g in self.net.layers[i].regularization_grad(sub).items():
                dp[str(i)][k] = dp[str(i)][k] + g
        return dp

    def _stage_has_reg(self, s):
        return any(getattr(self.net.layers[i], f, None)
                   for i in self.stages[s]
                   for f in ("l1", "l2", "l1_bias", "l2_bias"))

    def _is_last(self, s):
        return s == len(self.stages) - 1

    def _last_stage_loss(self, s, params, state, x, y, rng):
        out_idx = self.stages[s][-1]
        conf = self.net.conf
        x, new_state = self._apply_layers(self.stages[s][:-1], params,
                                          state, x, rng)
        out_layer = self.net.layers[out_idx]
        if out_idx in conf.preprocessors:
            from deeplearning4j_tpu.nn.conf.preprocessors import (
                preprocessor_key,
            )
            x = conf.preprocessors[out_idx].forward(
                x, rng=preprocessor_key(rng) if rng is not None else None)
        loss = jnp.mean(out_layer.compute_loss_per_example(
            params[str(out_idx)], x, y))
        return loss + self._stage_reg(s, params), new_state

    def _make_fwd(self, s):
        if self._is_last(s):
            def fwd(params, state, x, y, rng):
                return self._last_stage_loss(s, params, state, x, y, rng)
            return jax.jit(fwd)

        def fwd(params, state, x, rng):
            return self._apply_layers(self.stages[s], params, state, x, rng)
        return jax.jit(fwd)

    def _make_bwd(self, s):
        if self._is_last(s):
            def bwd(params, state, x, y, rng):
                loss, (dp, dx) = jax.value_and_grad(
                    lambda p, xx: self._last_stage_loss(s, p, state, xx, y,
                                                        rng)[0],
                    argnums=(0, 1))(params, x)
                dp = self._add_stage_reg_grads(s, params, dp)
                return loss, dp, dx
            return jax.jit(bwd)

        has_reg = self._stage_has_reg(s)

        def bwd(params, state, x, dy, rng):
            # recompute-forward vjp: only the stage INPUT was stashed
            y, vjp = jax.vjp(
                lambda p, xx: self._apply_layers(self.stages[s], p, state,
                                                 xx, rng)[0],
                params, x)
            dp, dx = vjp(dy)
            if has_reg:
                # the reg term does not flow through dy — add its local
                # closed-form gradient directly (single-device adds it to
                # the loss the same way)
                dp = self._add_stage_reg_grads(s, params, dp)
            return dp, dx
        return jax.jit(bwd)

    def _make_update(self, s):
        from deeplearning4j_tpu.nn.gradient_normalization import (
            apply_gradient_normalization,
        )

        updater = self.updater
        layer_map = {str(i): self.net.layers[i] for i in self.stages[s]}
        full_mults = self.net._lr_mult_tree()
        lr_mults = ({k: full_mults[k] for k in layer_map}
                    if full_mults is not None else None)

        @jax.jit
        def upd(params, opt, grads, iteration):
            grads = apply_gradient_normalization(layer_map, grads)
            if lr_mults is not None:
                steps, new_opt = updater.step(grads, opt, iteration,
                                              lr_mults)
            else:
                steps, new_opt = updater.step(grads, opt, iteration)
            new_p = jax.tree_util.tree_map(lambda p, st: p - st, params,
                                           steps)
            return new_p, new_opt
        return upd

    # ---------------------------------------------------------------- step
    def _microbatches(self, x, y):
        B = x.shape[0]
        if B % self.n_micro:
            raise ValueError(f"batch {B} not divisible by n_micro "
                             f"{self.n_micro}")
        m = B // self.n_micro
        return [(x[i * m:(i + 1) * m], y[i * m:(i + 1) * m])
                for i in range(self.n_micro)]

    def _rng(self, m, s):
        """Per-(microbatch, stage) dropout key, derived per iteration the
        way MultiLayerNetwork.do_step derives its per-step key. Stochastic
        layers therefore WORK under the pipeline, with a different (but
        equally fresh) key structure than single-device — bitwise parity
        holds for deterministic nets (the tested contract)."""
        base = jax.random.fold_in(jax.random.PRNGKey(self.net.conf.seed),
                                  self.iteration)
        return jax.random.fold_in(base, m * len(self.stages) + s)

    def do_step(self, x, y) -> float:
        x = np.asarray(x)
        y = np.asarray(y)
        micros = self._microbatches(x, y)
        S = len(self.stages)
        # forward schedule: async dispatch pipelines the (m, s) grid; the
        # stashes hold each stage's INPUT per microbatch for the backward
        stash = [[None] * S for _ in range(self.n_micro)]
        losses = []
        for m, (xm, ym) in enumerate(micros):
            a = jax.device_put(jnp.asarray(xm), self.devices[0])
            for s in range(S - 1):
                stash[m][s] = a
                a, _ = self._fwd[s](self._params[s], self._states[s], a,
                                    self._rng(m, s))
                a = jax.device_put(a, self.devices[s + 1])
            stash[m][S - 1] = (a, jax.device_put(jnp.asarray(ym),
                                                 self.devices[S - 1]))
        # backward schedule: per microbatch from the loss stage down,
        # accumulating per-stage gradients on their own devices
        grads = [None] * S
        for m in range(self.n_micro):
            a, ym = stash[m][S - 1]
            loss, dp, dx = self._bwd[S - 1](self._params[S - 1],
                                            self._states[S - 1], a, ym,
                                            self._rng(m, S - 1))
            losses.append(loss)
            grads[S - 1] = dp if grads[S - 1] is None else \
                jax.tree_util.tree_map(jnp.add, grads[S - 1], dp)
            dy = dx
            for s in range(S - 2, -1, -1):
                dy = jax.device_put(dy, self.devices[s])
                dp, dx = self._bwd[s](self._params[s], self._states[s],
                                      stash[m][s], dy, self._rng(m, s))
                grads[s] = dp if grads[s] is None else \
                    jax.tree_util.tree_map(jnp.add, grads[s], dp)
                dy = dx
        # sum of per-microbatch mean-loss grads / M == full-batch mean grad
        inv_m = 1.0 / self.n_micro
        # updaters take the 0-based iteration (Adam's t = iteration + 1),
        # matching MultiLayerNetwork.do_step's convention exactly
        it = jnp.float32(self.iteration)
        for s in range(S):
            g = jax.tree_util.tree_map(lambda t: t * inv_m, grads[s])
            self._params[s], self._opt[s] = self._upd[s](
                self._params[s], self._opt[s], g, it)
        # refresh layer states (BN running stats, ...) from the last
        # microbatch's forward — INCLUDING the last stage's body layers
        for s in range(S - 1):
            _, ns = self._fwd[s](self._params[s], self._states[s],
                                 stash[-1][s], self._rng(self.n_micro - 1, s))
            self._states[s] = ns
        a, ym = stash[-1][S - 1]
        _, ns = self._fwd[S - 1](self._params[S - 1], self._states[S - 1],
                                 a, ym, self._rng(self.n_micro - 1, S - 1))
        self._states[S - 1].update(ns)
        self.iteration += 1
        self.score_value = float(np.mean([float(l) for l in losses]))
        return self.score_value

    def fit(self, data, epochs: int = 1) -> "PipelineTrainer":
        from deeplearning4j_tpu.datasets.dataset import DataSet

        for _ in range(epochs):
            if isinstance(data, DataSet):
                self.do_step(data.features, data.labels)
            else:
                if hasattr(data, "reset"):
                    data.reset()
                for ds in data:
                    self.do_step(ds.features, ds.labels)
        self._sync_back()
        return self

    # ------------------------------------------------------------- plumbing
    def _sync_back(self):
        """Write stage params/state back into the wrapped net (so
        output/evaluate/serialization see the trained weights)."""
        for s, idxs in enumerate(self.stages):
            for i in idxs:
                self.net.params[str(i)] = jax.device_put(
                    self._params[s][str(i)], self.devices[0])
                if self._is_last(s) and i == idxs[-1]:
                    continue
                if str(i) in self._states[s]:
                    self.net.state[str(i)] = jax.device_put(
                        self._states[s][str(i)], self.devices[0])
        self.net.iteration = self.iteration
        self.net.score_value = self.score_value
