"""Cross-host fleet federation: survive whole-process death with
bit-exact cross-host migration.

``FleetFederation`` is a router in front of N ``FleetHost`` processes,
each wrapping a full ``ReplicaFleet`` behind a length-prefixed framed
RPC (submit / adopt / stats / retire / drain).  The honest CI proxy for
"hosts" is separate Python processes on localhost sockets
(``spawn_host`` launches ``python -m deeplearning4j_tpu.parallel.
federation --spec ...``); every failure mode the router handles — a
refused connect, a half-open link, a SIGKILLed process mid-stream — is
the real kernel artifact, not a mock.

Layered on the existing machinery rather than re-inventing it:

* **Routing** mirrors ``ReplicaFleet._route_once`` one level up:
  ``score = (inflight + 1) * max(ewma_ms, 0.5) * (1 + 8 * fail_ewma)``
  at host granularity, with a per-host ``CircuitBreaker`` +
  ``RetryPolicy`` and remaining-deadline propagation on every RPC.

* **Health gossip** rides ``parallel.elastic``: each host process runs
  a ``Heartbeat`` file writer; the router's ``FailureDetector`` answers
  both a short *suspect* question and a long *dead* question off the
  same observation table, so a wedged host is marked SUSPECT on missed
  beats BEFORE any TCP error surfaces.  Periodic ``stats`` RPCs roll
  every host's fleet stats — and its full metrics families — up to the
  router, so one ``GET /metrics`` scrape on the router shows every host
  (``metrics_sources()`` feeds the injected-``host=`` labels merge in
  ``metrics.exposition.render_text``).

* **Crash robustness** is the headline: hosts publish each in-flight
  request's newest periodic ``KVSnapshot`` (``snapshot_every=`` exports
  mirrored onto the fleet future by ``ReplicaFleet._monitor_tick``) to
  the router as opaque wire-v3 bytes.  When a host process dies
  mid-stream the router harvests each victim's newest snapshot and
  re-adopts it on a surviving host via ``ReplicaFleet.adopt`` — the
  completion is bit-exact either way (the fold_in key schedule makes
  token-0 regeneration exact; the snapshot only saves the recompute),
  checksum/geometry refusal falls back to token-0, and the federated
  ledger balances: ``submitted == completed + failed + expired +
  rejected_submits`` with zero lost futures.

* **Degraded mode** mirrors the fleet's decode-tier-dark flip: a
  multi-host federation down to <= 1 READY host raises the
  ``fed_degraded_mode`` gauge and logs the typed transition once per
  flip, auto-clearing on host recovery.

The router never touches device state: snapshots transit as opaque
bytes and are only parsed (header-only, via ``peek_snapshot``) for
observability.
"""

from __future__ import annotations

import argparse
import importlib
import json
import logging
import os
import select
import socket
import struct
import subprocess
import sys
import threading
import time
from collections import deque
from concurrent.futures import Future
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from deeplearning4j_tpu.metrics.registry import MetricsRegistry
from deeplearning4j_tpu.parallel.elastic import FailureDetector, Heartbeat
from deeplearning4j_tpu.parallel.fleet import ReplicaFleet
from deeplearning4j_tpu.parallel.handoff import (KVSnapshot, SnapshotError,
                                                 peek_snapshot)
from deeplearning4j_tpu.parallel.resilience import (
    AdmissionController, ChaosPolicy, CircuitBreaker, CircuitOpen,
    Deadline, DeadlineExceeded, ReplicaKilled, ReplicaUnavailable,
    ResilienceError, RetryPolicy, ServerOverloaded,
    TransientDispatchError)
from deeplearning4j_tpu.parallel.runtime import EXIT, ServingLoop, supervisor
from deeplearning4j_tpu.streaming.broker import FrameTooLarge, read_exact

log = logging.getLogger("dl4j_tpu.federation")

__all__ = ["FleetFederation", "FleetHost", "HostHandle", "HostUnavailable",
           "FederationProtocolError", "spawn_host", "build_generation_fleet",
           "FED_MAX_FRAME_BYTES", "READY", "SUSPECT", "DEAD", "RETIRED"]

# host lifecycle states (router's view)
READY = "ready"
SUSPECT = "suspect"      # missed heartbeats / failed gossip, link not dead
DEAD = "dead"            # link down or heartbeat verdict; awaiting reconnect
RETIRED = "retired"      # deliberate removal; never reconnected

#: default defensive bound on one federation RPC frame — far above any
#: control message, comfortably above a test-scale KV snapshot, far
#: below the broker's 1 GiB streaming bound
FED_MAX_FRAME_BYTES = 1 << 26

_U32 = struct.Struct(">I")

_UNSET = object()


class HostUnavailable(ReplicaUnavailable):
    """No federated host can accept the request (all dead, suspect,
    retired, or refusing). HTTP mapping: 503."""


class FederationProtocolError(ResilienceError):
    """A federation RPC frame failed structural validation (bad header
    length, unreadable JSON, missing ``op``). The receiving side answers
    with a best-effort ``protocol_error`` frame and CLOSES the
    connection — after a corrupt frame the stream offsets can no longer
    be trusted. HTTP mapping: 502."""


# typed errors a host can report over the wire, reconstructed router-side
_WIRE_ERRORS: Dict[str, type] = {
    "DeadlineExceeded": DeadlineExceeded,
    "ServerOverloaded": ServerOverloaded,
    "CircuitOpen": CircuitOpen,
    "ReplicaUnavailable": ReplicaUnavailable,
    "ReplicaKilled": ReplicaKilled,
    "TransientDispatchError": TransientDispatchError,
    "HostUnavailable": HostUnavailable,
    "ValueError": ValueError,
    "RuntimeError": RuntimeError,
}

#: error types that mean "this host shed the request" — re-route, count
#: against the host breaker, but do not poison the link
_SHED_ERRORS = ("ServerOverloaded", "CircuitOpen", "ReplicaUnavailable",
                "HostUnavailable")


# --------------------------------------------------------------- framing

def _send_msg(sock: socket.socket, header: dict, blob: bytes = b"", *,
              chaos: Any = None,
              max_frame_bytes: int = FED_MAX_FRAME_BYTES) -> None:
    """One federation frame out: ``u32 payload_len | u32 header_len |
    JSON header | blob``.  The router-side ``ChaosPolicy`` network hooks
    fire here: an active partition window (or a fresh partition draw)
    raises ``OSError`` without writing a byte; a corrupt draw flips one
    bit inside the header-length field so the receiver's structural
    validation — not a crash — rejects the frame."""
    hb = json.dumps(header).encode()
    payload = _U32.pack(len(hb)) + hb + blob
    if len(payload) > max_frame_bytes:
        raise FrameTooLarge(
            f"frame of {len(payload)} bytes exceeds the "
            f"{max_frame_bytes}-byte bound")
    frame = _U32.pack(len(payload)) + payload
    if chaos is not None:
        if chaos.net_partitioned():
            raise OSError("chaos: link partitioned")
        mode = chaos.net_fault_mode(len(frame))
        if mode == "partition":
            raise OSError("chaos: link partitioned")
        if mode == "corrupt":
            buf = bytearray(frame)
            buf[5] ^= 0x40  # header_len high bits -> structural reject
            frame = bytes(buf)
    sock.sendall(frame)


def _read_msg(sock: socket.socket,
              max_frame_bytes: int = FED_MAX_FRAME_BYTES
              ) -> Optional[Tuple[dict, bytes]]:
    """One federation frame in. Returns ``(header, blob)`` or ``None``
    on a clean EOF.  Raises ``FrameTooLarge`` when the length header
    exceeds the bound (typed, BEFORE allocating the payload) and
    ``FederationProtocolError`` on any structural violation."""
    raw = read_exact(sock, _U32.size)
    if raw is None:
        return None
    (plen,) = _U32.unpack(raw)
    if plen > max_frame_bytes:
        raise FrameTooLarge(
            f"frame of {plen} bytes exceeds the "
            f"{max_frame_bytes}-byte bound")
    if plen < _U32.size:
        raise FederationProtocolError(
            f"frame payload of {plen} bytes cannot hold a header length")
    payload = read_exact(sock, plen)
    if payload is None:
        return None
    (hlen,) = _U32.unpack_from(payload, 0)
    if hlen > plen - _U32.size:
        raise FederationProtocolError(
            f"header length {hlen} overruns the {plen}-byte frame")
    try:
        header = json.loads(payload[_U32.size:_U32.size + hlen].decode())
    except Exception as e:
        raise FederationProtocolError(f"unreadable frame header: {e}")
    if not isinstance(header, dict) or "op" not in header:
        raise FederationProtocolError(
            "frame header must be a JSON object with an 'op'")
    return header, payload[_U32.size + hlen:]


def _json_safe(obj: Any) -> Any:
    """Recursively coerce a stats tree to plain JSON types (numpy
    scalars/arrays -> Python; unknown leaves -> ``str``)."""
    if isinstance(obj, dict):
        return {str(k): _json_safe(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple, set, frozenset)):
        return [_json_safe(v) for v in obj]
    if isinstance(obj, np.ndarray):
        return obj.tolist()
    if isinstance(obj, np.generic):
        return obj.item()
    if obj is None or isinstance(obj, (bool, int, float, str)):
        return obj
    return str(obj)


# -------------------------------------------------------------- FleetHost

class _HostConn:
    """One accepted router connection: a blocking reader loop and an
    inbox-mode writer loop (completion callbacks only enqueue; sendall
    happens off every lock, broker-style)."""

    __slots__ = ("sock", "reader", "writer", "alive")

    def __init__(self, sock: socket.socket):
        self.sock = sock
        self.reader: Optional[ServingLoop] = None
        self.writer: Optional[ServingLoop] = None
        self.alive = True


class _LiveReq:
    """One router-submitted request live on this host."""

    __slots__ = ("fut", "conn", "published")

    def __init__(self, fut: Future, conn: _HostConn):
        self.fut = fut
        self.conn = conn
        self.published = -1   # newest snapshot count already shipped


class FleetHost:
    """Serve one ``ReplicaFleet`` to a federation router over a framed
    localhost socket.  Usable two ways: in-process (fast tests — real
    sockets, no subprocess) and as the worker half of ``spawn_host``
    (the ``__main__`` CLI below), where a SIGKILL of the process is the
    real whole-host death the router must survive.

    Ops: ``submit`` (fleet.submit), ``adopt`` (wire-v3 snapshot bytes ->
    ``KVSnapshot.from_bytes`` -> ``fleet.adopt``; typed snapshot refusal
    travels back as an ``error`` frame), ``stats`` (JSON-safe
    ``fleet.stats()`` + metrics families), ``retire`` (migrate-out: every
    live request's newest snapshot ships to the router followed by a
    ``RequestMigrated`` error), ``drain`` (``fleet.drain``).

    A publish tick polls each live fleet future's ``_kv_snapshot``
    mirror and ships any NEWER snapshot to the router as opaque bytes —
    the crash-durable publication that makes cross-host re-adoption
    possible after this process dies without a goodbye."""

    def __init__(self, fleet: Any, *, hid: str, port: int = 0,
                 host: str = "127.0.0.1",
                 max_frame_bytes: int = FED_MAX_FRAME_BYTES,
                 publish_tick_s: float = 0.005,
                 heartbeat_path: Optional[str] = None,
                 heartbeat_interval: float = 0.05,
                 registry: Optional[MetricsRegistry] = None):
        self.fleet = fleet
        self.hid = str(hid)
        self.max_frame_bytes = int(max_frame_bytes)
        self._publish_tick_s = float(publish_tick_s)
        self._lock = threading.Lock()   # leaf: protects _conns/_live only
        self._conns: List[_HostConn] = []
        self._live: Dict[int, _LiveReq] = {}
        self._closing = False
        self.registry = registry if registry is not None else fleet.metrics

        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((host, int(port)))
        self._srv.listen(16)
        self.port = self._srv.getsockname()[1]

        self.heartbeat: Optional[Heartbeat] = None
        if heartbeat_path:
            self.heartbeat = Heartbeat(heartbeat_path,
                                       interval=heartbeat_interval).start()

        self._accept = ServingLoop(f"fedhost-accept-{self.hid}",
                                   tick=self._accept_tick)
        supervisor().watch(self._accept,
                           on_death=lambda lp, exc: not self._closing,
                           restart=True)
        self._accept.start()
        self._publish = ServingLoop(f"fedhost-publish-{self.hid}",
                                    tick=self._publish_tick)
        supervisor().watch(self._publish,
                           on_death=lambda lp, exc: not self._closing,
                           restart=True)
        self._publish.start()

    # ----------------------------------------------------------- loops
    def _accept_tick(self) -> bool:
        try:
            sock, _ = self._srv.accept()
        except OSError:
            return False  # listening socket closed: clean exit
        if self._closing:
            # close() shut the listening socket out from under a blocked
            # accept; a connection that raced through the wakeup is
            # refused, not served
            try:
                sock.close()
            except OSError:
                pass
            return False
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        conn = _HostConn(sock)
        conn.writer = ServingLoop(
            f"fedhost-writer-{self.hid}",
            handler=lambda frame, c=conn: self._write_one(c, frame))
        conn.writer.start()
        conn.reader = ServingLoop(
            f"fedhost-reader-{self.hid}",
            tick=lambda c=conn: self._reader_tick(c),
            wake=lambda c=conn: self._shut(c))
        conn.reader.start()
        with self._lock:
            self._conns.append(conn)
        return True

    def _write_one(self, conn: _HostConn, frame: bytes):
        try:
            conn.sock.sendall(frame)
        except OSError:
            return EXIT
        return None

    def _reader_tick(self, conn: _HostConn) -> bool:
        try:
            msg = _read_msg(conn.sock, self.max_frame_bytes)
        except (FrameTooLarge, FederationProtocolError) as e:
            # the stream offsets are untrustworthy after a bad frame:
            # answer typed, then close the connection
            self._enqueue(conn, {"op": "protocol_error", "etype":
                                 type(e).__name__, "message": str(e)})
            time.sleep(0.05)  # give the writer a beat to flush
            self._drop_conn(conn)
            return False
        except OSError:
            self._drop_conn(conn)
            return False
        if msg is None:
            self._drop_conn(conn)
            return False
        header, blob = msg
        try:
            self._handle(conn, header, blob)
        except Exception as e:   # a handler bug must not kill the link
            log.warning("fedhost %s: %s handler failed: %r",
                        self.hid, header.get("op"), e)
            self._enqueue(conn, {"op": "error", "id": header.get("id"),
                                 "etype": type(e).__name__,
                                 "message": str(e)})
        return True

    def _publish_tick(self) -> bool:
        if self._closing:
            return False
        with self._lock:
            todo = [(rid, lr, getattr(lr.fut, "_kv_snapshot", None))
                    for rid, lr in self._live.items()]
        for rid, lr, snap in todo:
            if snap is None or snap.count <= lr.published:
                continue
            lr.published = snap.count
            self._enqueue(lr.conn, {"op": "snapshot", "id": rid,
                                    "count": snap.count}, snap.to_bytes())
        time.sleep(self._publish_tick_s)
        return True

    # -------------------------------------------------------- handlers
    def _enqueue(self, conn: _HostConn, header: dict,
                 blob: bytes = b"") -> None:
        hb = json.dumps(header).encode()
        payload = _U32.pack(len(hb)) + hb + blob
        frame = _U32.pack(len(payload)) + payload
        try:
            conn.writer.put(frame)
        except Exception:
            pass  # writer already retired: the router link is gone

    def _handle(self, conn: _HostConn, header: dict, blob: bytes) -> None:
        op = header["op"]
        rid = header.get("id")
        if op == "submit":
            self._op_submit(conn, rid, header)
        elif op == "adopt":
            self._op_adopt(conn, rid, header, blob)
        elif op == "stats":
            self._enqueue(conn, {"op": "stats", "id": rid,
                                 "stats": _json_safe(self.fleet.stats()),
                                 "families": self._families()})
        elif op == "drain":
            ok = self.fleet.drain(timeout=header.get("timeout"))
            self._enqueue(conn, {"op": "ok", "id": rid, "ok": bool(ok)})
        elif op == "retire":
            n = self._migrate_out(conn) if header.get("migrate", True) else 0
            self._enqueue(conn, {"op": "ok", "id": rid, "migrated": n})
        else:
            self._enqueue(conn, {"op": "error", "id": rid,
                                 "etype": "FederationProtocolError",
                                 "message": f"unknown op {op!r}"})

    def _op_submit(self, conn: _HostConn, rid: int, header: dict) -> None:
        try:
            prompt = np.asarray(header["prompt"], dtype=np.int64)
            kwargs: Dict[str, Any] = {
                "temperature": header.get("temperature", 0.0),
                "top_k": header.get("top_k", 0),
                "seed": header.get("seed", 0),
            }
            if "eos_id" in header:
                kwargs["eos_id"] = header["eos_id"]
            fut = self.fleet.submit(prompt, header["max_tokens"],
                                    deadline_s=header.get("deadline_s"),
                                    **kwargs)
        except Exception as e:
            self._enqueue(conn, {"op": "error", "id": rid,
                                 "etype": type(e).__name__,
                                 "message": str(e)})
            return
        self._register(conn, rid, fut)

    def _op_adopt(self, conn: _HostConn, rid: int, header: dict,
                  blob: bytes) -> None:
        try:
            snap = KVSnapshot.from_bytes(blob)
            fut = self.fleet.adopt(snap,
                                   deadline_s=header.get("deadline_s"))
        except Exception as e:
            self._enqueue(conn, {"op": "error", "id": rid,
                                 "etype": type(e).__name__,
                                 "message": str(e)})
            return
        self._register(conn, rid, fut)

    def _register(self, conn: _HostConn, rid: int, fut: Future) -> None:
        lr = _LiveReq(fut, conn)
        with self._lock:
            self._live[rid] = lr
        fut.add_done_callback(
            lambda f, rid=rid: self._req_done(rid, f))

    def _req_done(self, rid: int, fut: Future) -> None:
        """Fleet future resolved: ship the outcome. Runs on whichever
        thread resolved the future — only enqueues, never blocks."""
        with self._lock:
            lr = self._live.pop(rid, None)
        if lr is None:
            return   # orphaned: migrated out or router link dropped
        if fut.cancelled():
            self._enqueue(lr.conn, {"op": "error", "id": rid,
                                    "etype": "CancelledError",
                                    "message": "request cancelled"})
            return
        exc = fut.exception()
        if exc is not None:
            hdr = {"op": "error", "id": rid, "etype": type(exc).__name__,
                   "message": str(exc)}
            snap = getattr(fut, "_kv_snapshot", None)
            blob = b""
            if snap is not None:
                hdr["snapshot_count"] = snap.count
                blob = snap.to_bytes()
            self._enqueue(lr.conn, hdr, blob)
            return
        tokens = fut.result()
        self._enqueue(lr.conn, {"op": "result", "id": rid,
                                "tokens": np.asarray(tokens).tolist()})

    def _migrate_out(self, conn: _HostConn) -> int:
        """Hand every live request back to the router: newest snapshot
        (when one was published) then a ``RequestMigrated`` error.  The
        underlying fleet attempts keep running to completion as orphaned
        compute — the fleet API has no mid-flight cancel — and their
        late results are dropped at ``_req_done``."""
        with self._lock:
            victims = list(self._live.items())
            self._live.clear()
        for rid, lr in victims:
            snap = getattr(lr.fut, "_kv_snapshot", None)
            hdr = {"op": "error", "id": rid, "etype": "RequestMigrated",
                   "message": f"host {self.hid} retiring: request "
                              f"migrated back to the router"}
            blob = b""
            if snap is not None:
                hdr["snapshot_count"] = snap.count
                blob = snap.to_bytes()
            self._enqueue(lr.conn, hdr, blob)
        return len(victims)

    def _families(self) -> list:
        regs, seen = [], set()
        for reg in (self.registry, getattr(self.fleet, "metrics", None)):
            if reg is not None and id(reg) not in seen:
                seen.add(id(reg))
                regs.append(reg)
        fams: list = []
        for reg in regs:
            fams.extend(reg._snapshot_families())
        return _json_safe(fams)

    # -------------------------------------------------------- lifecycle
    def _shut(self, conn: _HostConn) -> None:
        try:
            conn.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass

    def _drop_conn(self, conn: _HostConn) -> None:
        with self._lock:
            conn.alive = False
            if conn in self._conns:
                self._conns.remove(conn)
            orphans = [rid for rid, lr in self._live.items()
                       if lr.conn is conn]
            for rid in orphans:
                del self._live[rid]
        try:
            conn.sock.close()
        except OSError:
            pass
        if conn.writer is not None:
            try:
                conn.writer.close(timeout=1.0)
            except Exception:
                pass

    def stats(self) -> dict:
        with self._lock:
            live = len(self._live)
            conns = len(self._conns)
        return {"hid": self.hid, "port": self.port, "live": live,
                "connections": conns, "fleet": self.fleet.stats()}

    def close(self) -> None:
        """Graceful: stop serving, drop links. Does NOT close the fleet
        (the caller built it and may still drain it)."""
        self._closing = True
        if self.heartbeat is not None:
            self.heartbeat.stop()
        try:
            # shutdown() unblocks a pending accept(); close() alone
            # leaves the kernel socket accepting while the loop blocks
            self._srv.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._srv.close()
        except OSError:
            pass
        with self._lock:
            conns = list(self._conns)
        for conn in conns:
            self._drop_conn(conn)
        for loop in (self._accept, self._publish):
            try:
                loop.close(timeout=2.0)
            except Exception:
                pass

    def kill(self) -> None:
        """Abrupt in-process death drill: heartbeat stops, every socket
        dies, no goodbye frames — the closest a same-process test can
        get to SIGKILL. The fleet is closed too (its futures die with
        the 'process')."""
        self._closing = True
        if self.heartbeat is not None:
            self.heartbeat.stop()
        try:
            self._srv.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._srv.close()
        except OSError:
            pass
        with self._lock:
            conns = list(self._conns)
            self._live.clear()
        for conn in conns:
            self._shut(conn)
            try:
                conn.sock.close()
            except OSError:
                pass
        for loop in (self._accept, self._publish):
            try:
                loop.close(timeout=2.0)
            except Exception:
                pass
        try:
            self.fleet.close(timeout=10.0)
        except Exception:
            pass

    def __enter__(self) -> "FleetHost":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


# -------------------------------------------------------- FleetFederation

class _FedRequest:
    """One caller request, owned by the router across host deaths."""

    __slots__ = ("prompt", "max_tokens", "kwargs", "deadline", "future",
                 "resolved", "hid", "rpc_id", "attempts", "snapshot_blob",
                 "snapshot_count", "resumed", "last_error", "t_submit",
                 "t_dispatch")

    def __init__(self, prompt, max_tokens: int, kwargs: dict,
                 deadline: Optional[Deadline], future: Future):
        self.prompt = prompt
        self.max_tokens = max_tokens
        self.kwargs = kwargs
        self.deadline = deadline
        self.future = future
        self.resolved = False
        self.hid: Optional[str] = None       # host currently serving it
        self.rpc_id: Optional[int] = None
        self.attempts = 0
        self.snapshot_blob: Optional[bytes] = None  # opaque wire-v3 bytes
        self.snapshot_count = -1
        self.resumed = False     # this dispatch rode a harvested snapshot
        self.last_error: Optional[BaseException] = None
        self.t_submit = time.monotonic()
        self.t_dispatch = 0.0


class _Host:
    """Router-side record of one federated host."""

    __slots__ = ("hid", "addr", "state", "sock", "reader", "io_lock",
                 "inflight", "ewma_ms", "fail_ewma", "breaker", "retry",
                 "dispatched", "completed", "failed", "rejected",
                 "stats", "families", "suspect_reason", "warned_suspect",
                 "reconnects", "next_reconnect_at", "backoff_s",
                 "last_stats_sent", "generation")

    def __init__(self, hid: str, addr: Tuple[str, int],
                 breaker: CircuitBreaker, retry: RetryPolicy):
        self.hid = hid
        self.addr = addr
        self.state = DEAD
        self.sock: Optional[socket.socket] = None
        self.reader: Optional[ServingLoop] = None
        self.io_lock = threading.Lock()   # leaf: serializes sendall
        self.inflight = 0
        self.ewma_ms = 0.0
        self.fail_ewma = 0.0
        self.breaker = breaker
        self.retry = retry
        self.dispatched = 0
        self.completed = 0
        self.failed = 0
        self.rejected = 0
        self.stats: Optional[dict] = None      # last gossip rollup
        self.families: Optional[list] = None   # last metrics families
        self.suspect_reason: Optional[str] = None
        self.warned_suspect = False
        self.reconnects = 0
        self.next_reconnect_at = 0.0
        self.backoff_s = 0.0
        self.last_stats_sent = 0.0
        self.generation = 0   # bumps per (re)connect; stales old readers


def _score_host(h: _Host) -> float:
    """Same shape as ``ReplicaFleet._score`` one level up: pending work
    x expected latency x failure penalty."""
    return ((h.inflight + 1) * max(h.ewma_ms, 0.5)
            * (1.0 + 8.0 * h.fail_ewma))


class FleetFederation:
    """Route requests across N ``FleetHost`` endpoints; survive whole-
    host death with bit-exact cross-host snapshot adoption.

    ``hosts`` items may be ``FleetHost`` instances (in-process),
    ``HostHandle`` (spawned processes), or ``(hid, port)`` /
    ``(hid, host, port)`` tuples.  The federation owns its links and its
    ledger, NOT the host processes — killing/closing those is the
    caller's business (and the failure drill's)."""

    def __init__(self, hosts: Sequence[Any], *, max_pending: int = 256,
                 gossip_tick_s: float = 0.05,
                 stats_every_s: float = 0.25,
                 suspect_after_s: float = 0.5,
                 dead_after_s: float = 30.0,
                 heartbeat_dir: Optional[str] = None,
                 reconnect_backoff_s: float = 0.2,
                 reconnect_backoff_cap_s: float = 2.0,
                 max_redispatch: Optional[int] = None,
                 health_alpha: float = 0.25,
                 breaker_factory: Optional[Callable[[], CircuitBreaker]]
                 = None,
                 retry_factory: Optional[Callable[[], RetryPolicy]] = None,
                 max_frame_bytes: int = FED_MAX_FRAME_BYTES,
                 chaos: Any = None,
                 registry: Optional[MetricsRegistry] = None):
        if not hosts:
            raise ValueError("need at least one host")
        self._gossip_tick_s = float(gossip_tick_s)
        self._stats_every_s = float(stats_every_s)
        self._suspect_after_s = float(suspect_after_s)
        self._dead_after_s = float(dead_after_s)
        self._reconnect_backoff_s = float(reconnect_backoff_s)
        self._reconnect_backoff_cap_s = float(reconnect_backoff_cap_s)
        self._max_redispatch = (None if max_redispatch is None
                                else int(max_redispatch))
        self._alpha = float(health_alpha)
        self.max_frame_bytes = int(max_frame_bytes)
        self._chaos = chaos
        self._detector = (FailureDetector(heartbeat_dir,
                                          timeout=dead_after_s)
                          if heartbeat_dir else None)
        self.admission = AdmissionController(max_pending=max_pending)
        self._cond = threading.Condition()
        self._closing = False
        self._degraded = False
        self._hosts: Dict[str, _Host] = {}
        self._rpc: Dict[int, _FedRequest] = {}
        self._ctrl: Dict[int, dict] = {}
        self._parked: deque = deque()
        self._next_id = 0
        self._wake = threading.Event()

        self.metrics = registry if registry is not None \
            else MetricsRegistry()
        m = self.metrics
        self._m_submitted = m.counter(
            "fed_submitted_total", "requests offered to the federation")
        self._m_rejected_submits = m.counter(
            "fed_rejected_submits_total",
            "submits shed typed before acceptance")
        self._m_completed = m.counter(
            "fed_completed_total", "requests completed")
        self._m_failed = m.counter(
            "fed_failed_total", "requests failed on error")
        self._m_expired = m.counter(
            "fed_expired_total", "requests failed on deadline")
        self._m_redispatched = m.counter(
            "fed_redispatched_total",
            "dispatch attempts re-routed to another host")
        self._m_deaths = m.counter(
            "fed_host_deaths_total", "host links declared dead")
        self._m_reconnects = m.counter(
            "fed_host_reconnects_total", "host links re-established")
        self._m_migrated = m.counter(
            "fed_migrated_total", "requests handed back by retiring hosts")
        self._m_resumes = m.counter(
            "fed_handoff_resumes_total",
            "cross-host dispatches that rode a harvested snapshot")
        self._m_fallbacks = m.counter(
            "fed_handoff_fallbacks_total",
            "snapshot adoptions refused typed; replayed from token 0")
        self._m_snapshots = m.counter(
            "fed_snapshots_total", "snapshot frames received from hosts")
        self._m_proto_errors = m.counter(
            "fed_protocol_errors_total",
            "frames rejected by structural validation (either side)")
        m.gauge("fed_hosts_ready", "hosts in READY",
                fn=lambda: self._count_state(READY))
        m.gauge("fed_hosts_suspect", "hosts in SUSPECT",
                fn=lambda: self._count_state(SUSPECT))
        m.gauge("fed_degraded_mode",
                "1 while a multi-host federation is down to <=1 READY "
                "host", fn=lambda: 1.0 if self._degraded else 0.0)
        m.gauge("fed_parked", "requests parked awaiting re-route",
                fn=lambda: self._parked_len())
        m.gauge("fed_inflight", "unresolved federated requests",
                fn=lambda: self._inflight_len())

        breaker_factory = breaker_factory or CircuitBreaker
        retry_factory = retry_factory or (lambda: RetryPolicy(
            max_attempts=2, retry_on=(TransientDispatchError,)))
        with self._cond:
            for item in hosts:
                hid, addr = self._host_endpoint(item)
                if hid in self._hosts:
                    raise ValueError(f"duplicate host id {hid!r}")
                self._hosts[hid] = _Host(hid, addr, breaker_factory(),
                                         retry_factory())
        for h in self._hosts.values():
            try:
                self._connect_host(h)
            except OSError as e:
                log.warning("federation: initial connect to %s failed "
                            "(%r); will retry", h.hid, e)
                self._schedule_reconnect(h)

        self._gossip = ServingLoop("federation-gossip",
                                   tick=self._gossip_loop,
                                   wake=self._wake.set)
        supervisor().watch(self._gossip,
                           on_death=lambda lp, exc: not self._closing,
                           restart=True)
        self._gossip.start()

    # ------------------------------------------------------- endpoints
    @staticmethod
    def _host_endpoint(item: Any) -> Tuple[str, Tuple[str, int]]:
        hid = getattr(item, "hid", None)
        port = getattr(item, "port", None)
        if hid is not None and port is not None:
            return str(hid), ("127.0.0.1", int(port))
        if isinstance(item, (tuple, list)):
            if len(item) == 2:
                return str(item[0]), ("127.0.0.1", int(item[1]))
            if len(item) == 3:
                return str(item[0]), (str(item[1]), int(item[2]))
        raise ValueError(f"cannot derive a host endpoint from {item!r}")

    def _count_state(self, state: str) -> int:
        with self._cond:
            return sum(1 for h in self._hosts.values()
                       if h.state == state)

    def _parked_len(self) -> int:
        with self._cond:
            return len(self._parked)

    def _inflight_len(self) -> int:
        with self._cond:
            return len(self._rpc) + len(self._parked)

    # ---------------------------------------------------------- links
    def _connect_host(self, h: _Host) -> None:
        """Dial the host. Raises OSError (incl. chaos conn-refused) on
        failure; on success the host is READY with a fresh reader."""
        if self._chaos is not None:
            self._chaos.net_connect_fault()
        sock = socket.create_connection(h.addr, timeout=5.0)
        if sock.getsockname() == sock.getpeername():
            # TCP simultaneous-open self-connect: retrying a freed
            # ephemeral port can land the outgoing socket on its own
            # source port, so connect() "succeeds" against a dead host.
            # Anything sent would echo straight back to the reader.
            sock.close()
            raise OSError(f"host {h.hid}: self-connect to {h.addr}, "
                          "no listener")
        sock.settimeout(None)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        with self._cond:
            h.sock = sock
            h.generation += 1
            h.state = READY
            h.backoff_s = 0.0
            h.suspect_reason = None
            h.warned_suspect = False
            gen = h.generation
        reader = ServingLoop(
            f"fed-link-{h.hid}-g{gen}",
            tick=lambda: self._link_tick(h, sock),
            wake=lambda s=sock: self._shut_sock(s))
        supervisor().watch(
            reader,
            on_death=lambda lp, exc, hh=h, ss=sock:
                self._reader_died(hh, ss, exc),
            restart=False)
        with self._cond:
            h.reader = reader
        reader.start()

    @staticmethod
    def _shut_sock(sock: socket.socket) -> None:
        try:
            sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass

    def _reader_died(self, h: _Host, sock: socket.socket,
                     exc: BaseException) -> bool:
        log.warning("federation: link reader for %s crashed: %r",
                    h.hid, exc)
        self._host_link_failed(h, sock, exc)
        return False   # never restart a stale link reader

    def _link_tick(self, h: _Host, sock: socket.socket) -> bool:
        try:
            msg = _read_msg(sock, self.max_frame_bytes)
        except (FrameTooLarge, FederationProtocolError) as e:
            self._m_proto_errors.inc()
            self._host_link_failed(h, sock, e)
            return False
        except OSError as e:
            self._host_link_failed(h, sock, e)
            return False
        if msg is None:
            self._host_link_failed(h, sock,
                                   OSError("host closed the link"))
            return False
        header, blob = msg
        self._on_frame(h, header, blob)
        return True

    def _send_to(self, h: _Host, header: dict, blob: bytes = b"") -> None:
        """Serialize + send on the host link (io_lock held for the
        sendall; never under ``_cond``)."""
        with h.io_lock:
            sock = h.sock
            if sock is None:
                raise OSError(f"host {h.hid}: no link")
            _send_msg(sock, header, blob, chaos=self._chaos,
                      max_frame_bytes=self.max_frame_bytes)

    # -------------------------------------------------------- routing
    def submit(self, prompt_ids, max_tokens: int, *, temperature=0.0,
               top_k=0, seed=0, eos_id=_UNSET,
               deadline_s: Optional[float] = None) -> Future:
        """Route one generation request to the healthiest host. The
        returned Future survives whole-host death (harvest + re-adopt /
        token-0 replay on a survivor) and fails only typed."""
        prompt = np.asarray(prompt_ids, dtype=np.int64)
        if prompt.ndim != 1 or prompt.shape[0] < 1:
            raise ValueError("prompt_ids must be a non-empty 1-D id list")
        if deadline_s is not None and deadline_s <= 0:
            raise ValueError("deadline_s must be positive")
        kwargs: Dict[str, Any] = {"temperature": float(temperature),
                                  "top_k": int(top_k), "seed": int(seed)}
        if eos_id is not _UNSET:
            kwargs["eos_id"] = eos_id
        with self._cond:
            if self._closing:
                raise RuntimeError("FleetFederation is closed")
        self.admission.acquire()
        fut = Future()
        fut.add_done_callback(lambda _f: self.admission.release())
        freq = _FedRequest(
            prompt, int(max_tokens), kwargs,
            None if deadline_s is None else Deadline(deadline_s), fut)
        self._m_submitted.inc()
        routed, reason = self._route_host(freq)
        if routed:
            return fut
        if reason == "breaker":
            exc: Exception = CircuitOpen(
                "every live host's circuit breaker is open")
        elif isinstance(freq.last_error, ResilienceError):
            exc = freq.last_error
        else:
            exc = HostUnavailable(
                "no federated host can accept the request")
        self._resolve(freq, None, exc, rejected=True)
        raise exc

    def _route_host(self, freq: _FedRequest) -> Tuple[bool, str]:
        """Dispatch ``freq`` to the best host right now.  Mirrors
        ``ReplicaFleet._route_once`` one level up: health-scored
        candidates, breaker gate, typed reason when nobody takes it.
        SUSPECT hosts serve only as a last resort when no READY host
        exists.  Send failures flip the host link dead (harvesting its
        other in-flight requests) and fall through to the next
        candidate. HOT: runs per dispatch on the serving path."""
        if freq.deadline is not None and freq.deadline.expired():
            self._resolve(freq, None, DeadlineExceeded(
                "deadline expired before dispatch"))
            return True, "expired"
        tried: set = set()
        saw_breaker = False
        while True:
            with self._cond:
                if self._closing:
                    return False, "closed"
                ready = [h for h in self._hosts.values()
                         if h.state == READY and h.hid not in tried]
                if not ready:
                    ready = [h for h in self._hosts.values()
                             if h.state == SUSPECT and h.hid not in tried]
                cands = sorted(ready, key=_score_host)
                target = None
                for h in cands:
                    if not h.breaker.allow():
                        saw_breaker = True
                        continue
                    target = h
                    break
                if target is None:
                    return False, ("breaker" if saw_breaker else "nohost")
                self._next_id += 1
                rid = self._next_id
                target.inflight += 1
                target.dispatched += 1
                freq.hid = target.hid
                freq.rpc_id = rid
                freq.attempts += 1
                freq.t_dispatch = time.monotonic()
                freq.resumed = freq.snapshot_blob is not None
                self._rpc[rid] = freq
                sock_gen = target.generation
            tried.add(target.hid)
            rem = (None if freq.deadline is None
                   else freq.deadline.remaining())
            if freq.snapshot_blob is not None:
                header = {"op": "adopt", "id": rid}
                if rem is not None:
                    header["deadline_s"] = max(rem, 0.001)
                blob = freq.snapshot_blob
            else:
                header = {"op": "submit", "id": rid,
                          "prompt": freq.prompt.tolist(),
                          "max_tokens": freq.max_tokens}
                header.update(freq.kwargs)
                if rem is not None:
                    if rem <= 0:
                        with self._cond:
                            self._rpc.pop(rid, None)
                            target.inflight -= 1
                        self._resolve(freq, None, DeadlineExceeded(
                            "deadline expired before dispatch"))
                        return True, "expired"
                    header["deadline_s"] = rem
                blob = b""
            try:
                target.retry.call(self._send_to, target, header, blob,
                                  deadline=freq.deadline)
            except (OSError, FrameTooLarge) as e:
                # the whole link is suspect, not just this request:
                # _host_link_failed harvests every in-flight request on
                # it (including this one) back to parked; re-park is
                # idempotent, so just unlink ours first and move on
                with self._cond:
                    self._rpc.pop(rid, None)
                    target.inflight -= 1
                freq.hid = None
                freq.rpc_id = None
                freq.last_error = e
                with self._cond:
                    sock = target.sock
                self._host_link_failed(target, sock, e,
                                       expected_gen=sock_gen)
                continue
            if freq.resumed:
                self._m_resumes.inc()
            return True, "dispatched"

    def _resolve(self, freq: _FedRequest, value, exc, *,
                 rejected: bool = False) -> None:
        """Resolve the caller future exactly once; keep the federated
        ledger balanced (submitted == completed + failed + expired +
        rejected_submits once idle)."""
        with self._cond:
            if freq.resolved:
                return
            freq.resolved = True
            if freq.rpc_id is not None:
                self._rpc.pop(freq.rpc_id, None)
            self._cond.notify_all()
        if exc is None and rejected:
            self._m_rejected_submits.inc()
            freq.future.cancel()
            return
        if exc is not None:
            if rejected:
                self._m_rejected_submits.inc()
            elif isinstance(exc, DeadlineExceeded):
                self._m_expired.inc()
            else:
                self._m_failed.inc()
            freq.future.set_exception(exc)
        else:
            self._m_completed.inc()
            freq.future.set_result(value)

    # --------------------------------------------------------- frames
    def _on_frame(self, h: _Host, header: dict, blob: bytes) -> None:
        op = header.get("op")
        rid = header.get("id")
        if op == "result":
            self._on_result(h, rid, header)
        elif op == "error":
            self._on_error(h, rid, header, blob)
        elif op == "snapshot":
            self._on_snapshot(h, rid, header, blob)
        elif op == "stats":
            self._on_stats(h, header)
        elif op == "ok":
            self._ctrl_reply(rid, header)
        elif op == "protocol_error":
            self._m_proto_errors.inc()
            log.warning("federation: host %s rejected a frame: %s",
                        h.hid, header.get("message"))
        else:
            log.warning("federation: unknown frame op %r from %s",
                        op, h.hid)

    def _take_rpc(self, h: _Host, rid) -> Optional[_FedRequest]:
        with self._cond:
            freq = self._rpc.pop(rid, None) if rid is not None else None
            if freq is not None:
                h.inflight = max(0, h.inflight - 1)
                freq.rpc_id = None
                freq.hid = None
        return freq

    def _on_result(self, h: _Host, rid, header: dict) -> None:
        freq = self._take_rpc(h, rid)
        if freq is None:
            return   # orphan: harvested earlier, duplicate resolved
        lat_ms = (time.monotonic() - freq.t_dispatch) * 1000.0
        with self._cond:
            h.completed += 1
            a = self._alpha
            h.ewma_ms = (lat_ms if h.ewma_ms == 0.0
                         else (1 - a) * h.ewma_ms + a * lat_ms)
            h.fail_ewma = (1 - a) * h.fail_ewma
        h.breaker.record_success()
        self._resolve(freq, np.asarray(header.get("tokens", []),
                                       dtype=np.int64), None)

    def _on_error(self, h: _Host, rid, header: dict,
                  blob: bytes) -> None:
        freq = self._take_rpc(h, rid)
        if freq is None:
            return
        etype = header.get("etype", "RuntimeError")
        message = header.get("message", "")
        if blob:
            count = header.get("snapshot_count", 0)
            if count > freq.snapshot_count:
                freq.snapshot_blob = blob
                freq.snapshot_count = count
        if etype == "RequestMigrated":
            self._m_migrated.inc()
            self._park(freq)
            return
        if etype in ("SnapshotInvalid", "SnapshotUnsupported",
                     "SnapshotError", "SnapshotUnavailable"):
            # the surviving host refused the harvested snapshot typed
            # (checksum, geometry, version): drop it and replay from
            # token 0 — bit-exact via the fold_in key schedule
            freq.snapshot_blob = None
            freq.snapshot_count = -1
            self._m_fallbacks.inc()
            self._park(freq)
            return
        if etype == "DeadlineExceeded":
            self._resolve(freq, None, DeadlineExceeded(message))
            return
        if etype == "ValueError":
            self._resolve(freq, None, ValueError(message))
            return
        if etype in _SHED_ERRORS:
            with self._cond:
                h.rejected += 1
            h.breaker.record_failure()
            freq.last_error = _WIRE_ERRORS.get(
                etype, ResilienceError)(message)
            self._park(freq)
            return
        # hard failure on that host (replica died past the fleet's own
        # budget, handler bug, cancelled): blame the host, try another
        with self._cond:
            h.failed += 1
            a = self._alpha
            h.fail_ewma = (1 - a) * h.fail_ewma + a
        h.breaker.record_failure()
        freq.last_error = _WIRE_ERRORS.get(
            etype, ResilienceError)(f"{etype} on host {h.hid}: {message}")
        self._park(freq)

    def _on_snapshot(self, h: _Host, rid, header: dict,
                     blob: bytes) -> None:
        try:
            # header-only structural check (opaque payload untouched):
            # a mangled blob is dropped here, never offered for adoption
            peek_snapshot(blob)
        except SnapshotError:
            self._m_proto_errors.inc()
            return
        self._m_snapshots.inc()
        with self._cond:
            freq = self._rpc.get(rid)
            if freq is None:
                return
            count = header.get("count", 0)
            if count > freq.snapshot_count:
                freq.snapshot_blob = blob
                freq.snapshot_count = count

    def _on_stats(self, h: _Host, header: dict) -> None:
        with self._cond:
            h.stats = header.get("stats")
            h.families = header.get("families")
            if h.state == SUSPECT and h.suspect_reason == "stats":
                h.state = READY
                h.suspect_reason = None
                h.warned_suspect = False
                log.warning("federation: host %s recovered (gossip "
                            "stats reply)", h.hid)
            self._note_degraded_locked()
        self._ctrl_reply(header.get("id"), header)

    def _ctrl_reply(self, rid, header: dict) -> None:
        if rid is None:
            return
        with self._cond:
            slot = self._ctrl.get(rid)
            if slot is None:
                return
            slot["reply"] = header
        slot["evt"].set()

    # ------------------------------------------------- death + harvest
    def _host_link_failed(self, h: _Host, sock, exc,
                          expected_gen: Optional[int] = None) -> None:
        """The link to ``h`` is gone (TCP error, EOF, poisoned stream,
        or a heartbeat dead-verdict): mark the host DEAD, harvest every
        in-flight request it held — each with its newest published
        snapshot already attached — and park them for re-route. HOT:
        this is the crash path the whole federation exists for."""
        with self._cond:
            if sock is not None and h.sock is not sock:
                return   # stale reader of a replaced link
            if expected_gen is not None and h.generation != expected_gen:
                return
            if h.state in (DEAD, RETIRED):
                return
            h.state = DEAD
            old_sock = h.sock
            h.sock = None
            victims = self._harvest_host(h)
            self._note_degraded_locked()
        self._m_deaths.inc()
        log.warning("federation: host %s is DEAD (%r); harvested %d "
                    "in-flight request(s)", h.hid, exc, len(victims))
        if old_sock is not None:
            try:
                old_sock.close()
            except OSError:
                pass
        for freq in victims:
            self._m_redispatched.inc()
        self._schedule_reconnect(h)
        self._wake.set()

    def _harvest_host(self, h: _Host) -> List[_FedRequest]:
        """Collect every in-flight request owned by ``h`` off the rpc
        table and park it (``_cond`` held).  Snapshots harvested from
        the host's periodic publications ride along on each request, so
        the re-route adopts at position N instead of replaying. HOT."""
        victims = [freq for freq in self._rpc.values() if freq.hid == h.hid]
        for freq in victims:
            self._rpc.pop(freq.rpc_id, None)
            freq.rpc_id = None
            freq.hid = None
            self._parked.append(freq)
        h.inflight = 0
        return victims

    def _park(self, freq: _FedRequest) -> None:
        if (self._max_redispatch is not None
                and freq.attempts > self._max_redispatch):
            exc = freq.last_error or HostUnavailable(
                "redispatch budget exhausted")
            self._resolve(freq, None, exc)
            return
        if freq.deadline is not None and freq.deadline.expired():
            self._resolve(freq, None, DeadlineExceeded(
                f"deadline expired after {freq.attempts} attempt(s)"))
            return
        with self._cond:
            self._parked.append(freq)
        self._m_redispatched.inc()
        self._wake.set()

    def _schedule_reconnect(self, h: _Host) -> None:
        with self._cond:
            h.backoff_s = (self._reconnect_backoff_s if h.backoff_s == 0.0
                           else min(h.backoff_s * 2.0,
                                    self._reconnect_backoff_cap_s))
            h.next_reconnect_at = time.monotonic() + h.backoff_s

    # --------------------------------------------------------- gossip
    def _gossip_loop(self) -> bool:
        """One supervised router tick: heartbeat suspect/dead verdicts,
        periodic stats gossip, dead-host reconnect, degraded-mode eval,
        and parked-request service.  Paced by ``_wake`` so a harvest or
        park is serviced immediately instead of next tick. HOT: every
        recovery decision the federation makes happens here."""
        self._wake.wait(self._gossip_tick_s)
        self._wake.clear()
        with self._cond:
            if self._closing:
                return False
        now = time.monotonic()

        # 1) heartbeat gossip: SUSPECT on missed beats BEFORE any TCP
        #    error; DEAD on the long verdict
        if self._detector is not None:
            suspects = set(self._detector.dead_workers(
                timeout=self._suspect_after_s))
            deads = set(self._detector.dead_workers(
                timeout=self._dead_after_s))
            for h in self._live_hosts():
                if h.hid in deads:
                    with self._cond:
                        sock = h.sock
                    self._host_link_failed(
                        h, sock, OSError("heartbeat dead verdict"))
                elif h.hid in suspects:
                    self._mark_suspect(h, "heartbeat")
                else:
                    with self._cond:
                        if (h.state == SUSPECT
                                and h.suspect_reason == "heartbeat"):
                            h.state = READY
                            h.suspect_reason = None
                            h.warned_suspect = False
                            log.warning("federation: host %s recovered "
                                        "(heartbeat fresh)", h.hid)
                            self._note_degraded_locked()

        # 2) stats gossip rollups
        for h in self._live_hosts():
            if now - h.last_stats_sent < self._stats_every_s:
                continue
            h.last_stats_sent = now
            with self._cond:
                self._next_id += 1
                rid = self._next_id
            try:
                self._send_to(h, {"op": "stats", "id": rid})
            except OSError:
                self._mark_suspect(h, "stats")

        # 3) reconnect DEAD hosts past backoff (partition heal;
        #    a SIGKILLed process keeps refusing -> stays DEAD)
        for h in self._dead_hosts():
            if now < h.next_reconnect_at:
                continue
            try:
                self._connect_host(h)
            except OSError:
                self._schedule_reconnect(h)
                continue
            with self._cond:
                h.reconnects += 1
                self._note_degraded_locked()
            self._m_reconnects.inc()
            log.warning("federation: host %s reconnected", h.hid)

        # 4) serve parked requests
        self._service_parked_fed()
        return True

    def _live_hosts(self) -> List[_Host]:
        with self._cond:
            return [h for h in self._hosts.values()
                    if h.state in (READY, SUSPECT)]

    def _dead_hosts(self) -> List[_Host]:
        with self._cond:
            return [h for h in self._hosts.values() if h.state == DEAD]

    def _mark_suspect(self, h: _Host, reason: str) -> None:
        with self._cond:
            if h.state != READY:
                return
            h.state = SUSPECT
            h.suspect_reason = reason
            warn = not h.warned_suspect
            h.warned_suspect = True
            self._note_degraded_locked()
        if warn:
            log.warning("federation: host %s SUSPECT (%s) — routing "
                        "around it before any TCP error surfaces",
                        h.hid, reason)

    def _note_degraded_locked(self) -> None:
        """Degraded-mode flip (``_cond`` held): a multi-host federation
        down to <=1 READY host serves degraded, mirroring the fleet's
        decode-tier-dark transition — typed log once per flip, gauge
        auto-clears on host recovery."""
        if len(self._hosts) <= 1:
            return
        ready = sum(1 for h in self._hosts.values() if h.state == READY)
        dark = ready <= 1
        if dark == self._degraded:
            return
        self._degraded = dark
        if dark:
            log.warning(
                "federation degraded mode ENTERED: %d/%d hosts READY; "
                "serving on the survivor(s)", ready, len(self._hosts))
        else:
            log.warning("federation degraded mode cleared: %d/%d hosts "
                        "READY", ready, len(self._hosts))

    def _service_parked_fed(self) -> None:
        """Re-route every parked request once; requests that still find
        no host stay parked (zero lost futures — they fail only on
        deadline, redispatch budget, or close)."""
        while True:
            with self._cond:
                if not self._parked:
                    return
                freq = self._parked.popleft()
            if freq.resolved:
                continue
            if freq.deadline is not None and freq.deadline.expired():
                self._resolve(freq, None, DeadlineExceeded(
                    f"deadline expired after {freq.attempts} attempt(s)"))
                continue
            routed, reason = self._route_host(freq)
            if not routed:
                with self._cond:
                    self._parked.appendleft(freq)
                return

    # ------------------------------------------------------- control
    def _control(self, h: _Host, header: dict,
                 timeout: float = 10.0) -> Optional[dict]:
        with self._cond:
            self._next_id += 1
            rid = self._next_id
            slot = {"evt": threading.Event(), "reply": None}
            self._ctrl[rid] = slot
        header = dict(header)
        header["id"] = rid
        try:
            self._send_to(h, header)
            if not slot["evt"].wait(timeout):
                return None
            return slot["reply"]
        finally:
            with self._cond:
                self._ctrl.pop(rid, None)

    def retire_host(self, hid: str, *, migrate: bool = True,
                    timeout: float = 10.0) -> bool:
        """Deliberately remove a host: no new routing, then ask it to
        hand back its in-flight work (each request returns as a
        ``RequestMigrated`` error with its newest snapshot and resumes
        on a surviving host)."""
        with self._cond:
            h = self._hosts.get(hid)
            if h is None:
                raise KeyError(f"unknown host {hid!r}")
            prev = h.state
            h.state = RETIRED
            self._note_degraded_locked()
        if prev == DEAD or h.sock is None:
            return True
        reply = self._control(h, {"op": "retire", "migrate": migrate},
                              timeout=timeout)
        return reply is not None

    def host_stats(self, hid: str, *,
                   timeout: float = 10.0) -> Optional[dict]:
        """Fresh stats RPC to one host (gossip keeps a cached rollup;
        this forces a round trip)."""
        with self._cond:
            h = self._hosts.get(hid)
        if h is None:
            raise KeyError(f"unknown host {hid!r}")
        reply = self._control(h, {"op": "stats"}, timeout=timeout)
        return None if reply is None else reply.get("stats")

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Wait until every accepted request has resolved."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while self._rpc or self._parked:
                rem = (None if deadline is None
                       else deadline - time.monotonic())
                if rem is not None and rem <= 0:
                    return False
                self._cond.wait(rem if rem is not None else 0.5)
        return True

    # --------------------------------------------------------- stats
    def stats(self) -> dict:
        with self._cond:
            hosts = list(self._hosts.values())
            per = []
            for h in hosts:
                per.append({
                    "hid": h.hid,
                    "state": h.state,
                    "score": _score_host(h),
                    "ewma_latency_ms": h.ewma_ms,
                    "failure_ewma": h.fail_ewma,
                    "inflight": h.inflight,
                    "dispatched": h.dispatched,
                    "completed": h.completed,
                    "failed": h.failed,
                    "rejected": h.rejected,
                    "reconnects": h.reconnects,
                    "suspect_reason": h.suspect_reason,
                    "stats": h.stats,
                })
            ready = sum(1 for h in hosts if h.state == READY)
            suspect = sum(1 for h in hosts if h.state == SUSPECT)
            parked = len(self._parked)
            inflight = len(self._rpc)
            degraded = self._degraded
        for blk, h in zip(per, hosts):
            blk["breaker"] = h.breaker.state
        out = {
            "federation": {
                "hosts": len(hosts),
                "ready": ready,
                "suspect": suspect,
                "deaths": int(self._m_deaths.value),
                "reconnects": int(self._m_reconnects.value),
                "submitted": int(self._m_submitted.value),
                "rejected_submits": int(self._m_rejected_submits.value),
                "completed": int(self._m_completed.value),
                "failed": int(self._m_failed.value),
                "expired": int(self._m_expired.value),
                "redispatched": int(self._m_redispatched.value),
                "migrated": int(self._m_migrated.value),
                "handoff_resumes": int(self._m_resumes.value),
                "handoff_fallbacks": int(self._m_fallbacks.value),
                "snapshots": int(self._m_snapshots.value),
                "parked": parked,
                "inflight": inflight,
                "degraded_mode": degraded,
            },
            "hosts": per,
            "admission": {"pending": self.admission.pending,
                          "accepted": self.admission.accepted,
                          "rejected": self.admission.rejected},
        }
        return out

    def metrics_sources(self) -> List[Tuple[dict, Any]]:
        """Sources for ``metrics.exposition.render_text``: the router's
        own registry plus each host's last gossiped families under an
        injected ``host=`` label — one scrape shows the whole fleet
        of fleets."""
        out: List[Tuple[dict, Any]] = [({}, self.metrics)]
        with self._cond:
            for h in self._hosts.values():
                if h.families:
                    out.append(({"host": h.hid}, h.families))
        return out

    # ------------------------------------------------------ lifecycle
    def close(self, timeout: float = 10.0) -> None:
        """Shut the router down: leftover requests fail typed (zero
        lost futures), links drop, loops retire. Host processes /
        in-process FleetHosts are NOT closed — the federation never
        owned them."""
        with self._cond:
            if self._closing:
                return
            self._closing = True
            leftovers = list(self._rpc.values()) + list(self._parked)
            self._rpc.clear()
            self._parked.clear()
            hosts = list(self._hosts.values())
        self._wake.set()
        for freq in leftovers:
            self._resolve(freq, None, HostUnavailable(
                "federation closed with the request unresolved"))
        for h in hosts:
            with self._cond:
                sock, reader = h.sock, h.reader
                h.sock = None
                h.state = RETIRED
            if sock is not None:
                try:
                    sock.close()
                except OSError:
                    pass
            if reader is not None:
                try:
                    reader.close(timeout=2.0)
                except Exception:
                    pass
        try:
            self._gossip.close(timeout=timeout)
        except Exception:
            pass

    def __enter__(self) -> "FleetFederation":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


# ------------------------------------------------------- host processes

class HostHandle:
    """One spawned fleet-host process."""

    __slots__ = ("hid", "port", "pid", "proc", "heartbeat_path")

    def __init__(self, hid: str, port: int, pid: int,
                 proc: subprocess.Popen,
                 heartbeat_path: Optional[str] = None):
        self.hid = hid
        self.port = port
        self.pid = pid
        self.proc = proc
        self.heartbeat_path = heartbeat_path

    @property
    def alive(self) -> bool:
        return self.proc.poll() is None

    def kill(self) -> None:
        """SIGKILL — the whole-process death the federation must
        survive. No flush, no goodbye: the kernel resets the sockets
        and the heartbeat file goes stale where it stands."""
        self.proc.kill()
        self.proc.wait(timeout=30)

    def terminate(self) -> None:
        self.proc.terminate()
        try:
            self.proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            self.proc.kill()
            self.proc.wait(timeout=30)


def build_generation_fleet(*, vocab: int = 17, max_length: int = 16,
                           d_model: int = 16, n_heads: int = 2,
                           n_blocks: int = 1, net_seed: int = 3,
                           replicas: int = 2, slots: int = 4,
                           page_size: int = 16, snapshot_every: int = 0,
                           steps_per_dispatch: int = 4,
                           max_pending: int = 64,
                           fleet_max_pending: int = 256,
                           chaos: Optional[dict] = None,
                           chaos_seed_base: int = 1000) -> ReplicaFleet:
    """Default fleet builder for spawned host processes: a TransformerLM
    served by ``replicas`` GenerationServers.  ``chaos`` (a ChaosPolicy
    kwargs dict) seeds each replica's own deterministic injector off
    ``chaos_seed_base + rid`` — JSON-able, so it travels in the spawn
    spec."""
    from deeplearning4j_tpu.models.zoo import TransformerLM
    from deeplearning4j_tpu.parallel.generation import GenerationServer
    lm = TransformerLM(num_labels=vocab, max_length=max_length,
                       d_model=d_model, n_heads=n_heads,
                       n_blocks=n_blocks, seed=net_seed).init()

    def factory(rid: int):
        cp = (ChaosPolicy(seed=chaos_seed_base + rid, **chaos)
              if chaos else None)
        return GenerationServer(lm, vocab, slots=slots,
                                page_size=page_size,
                                snapshot_every=snapshot_every,
                                steps_per_dispatch=steps_per_dispatch,
                                max_pending=max_pending, chaos=cp)

    return ReplicaFleet(factory, replicas=replicas,
                        max_pending=fleet_max_pending)


def spawn_host(spec: dict, *, timeout: float = 180.0,
               env: Optional[dict] = None) -> HostHandle:
    """Launch one fleet-host process (``python -m deeplearning4j_tpu.
    parallel.federation --spec ...``) and wait for its READY line.

    ``spec`` keys: ``hid`` (required), ``port`` (default 0 = ephemeral),
    ``heartbeat_dir``, ``heartbeat_interval``, ``builder``
    (``"module:attr"``, default ``build_generation_fleet``),
    ``builder_kwargs``, ``max_frame_bytes``, ``publish_tick_s``.

    The child is forced onto CPU JAX and inherits the parent's x64
    flag, so cross-process generations stay bit-exact with the
    parent's references."""
    cmd = [sys.executable, "-m", "deeplearning4j_tpu.parallel.federation",
           "--spec", json.dumps(spec)]
    full_env = dict(os.environ)
    full_env.setdefault("JAX_PLATFORMS", "cpu")
    try:
        import jax
        if jax.config.jax_enable_x64:
            full_env.setdefault("JAX_ENABLE_X64", "true")
    except Exception:
        pass
    repo_root = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    prev = full_env.get("PYTHONPATH", "")
    full_env["PYTHONPATH"] = (repo_root + os.pathsep + prev
                              if prev else repo_root)
    if env:
        full_env.update(env)
    proc = subprocess.Popen(cmd, stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, env=full_env,
                            text=True, bufsize=1)
    deadline = time.monotonic() + timeout
    lines: List[str] = []
    ready: Optional[dict] = None
    while True:
        rem = deadline - time.monotonic()
        if rem <= 0:
            proc.kill()
            raise RuntimeError(
                f"fleet host {spec.get('hid')!r} did not become READY "
                f"within {timeout}s; output so far:\n" + "".join(lines))
        r, _, _ = select.select([proc.stdout], [], [], min(rem, 0.5))
        if not r:
            if proc.poll() is not None:
                raise RuntimeError(
                    f"fleet host {spec.get('hid')!r} exited rc="
                    f"{proc.returncode} before READY; output:\n"
                    + "".join(lines))
            continue
        line = proc.stdout.readline()
        if line == "":
            raise RuntimeError(
                f"fleet host {spec.get('hid')!r} closed stdout before "
                f"READY; output:\n" + "".join(lines))
        lines.append(line)
        if line.startswith("FLEETHOST READY "):
            fields = dict(kv.split("=", 1)
                          for kv in line.split()[2:])
            ready = {"hid": fields["hid"], "port": int(fields["port"]),
                     "pid": int(fields["pid"])}
            break

    def _drain():
        try:
            for _ in proc.stdout:
                pass
        except Exception:
            pass

    threading.Thread(target=_drain, daemon=True,
                     name=f"fedhost-stdout-{ready['hid']}").start()
    hb_path = None
    if spec.get("heartbeat_dir"):
        hb_path = os.path.join(spec["heartbeat_dir"],
                               f"{spec['hid']}.heartbeat")
    return HostHandle(ready["hid"], ready["port"], ready["pid"], proc,
                      heartbeat_path=hb_path)


def main(argv: Optional[Sequence[str]] = None) -> int:
    """Worker-process entrypoint: build the fleet named by the spec,
    serve it as a FleetHost, print the READY line, and block until
    killed. Deliberately boring — the interesting failure modes are
    inflicted on it from outside."""
    ap = argparse.ArgumentParser(
        description="serve one ReplicaFleet as a federation host")
    ap.add_argument("--spec", required=True,
                    help="JSON spec: hid/port/heartbeat_dir/builder/...")
    args = ap.parse_args(argv)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    spec = json.loads(args.spec)
    builder = spec.get(
        "builder",
        "deeplearning4j_tpu.parallel.federation:build_generation_fleet")
    mod_name, _, attr = builder.partition(":")
    builder_fn = getattr(importlib.import_module(mod_name), attr)
    fleet = builder_fn(**spec.get("builder_kwargs", {}))
    hb_path = None
    if spec.get("heartbeat_dir"):
        os.makedirs(spec["heartbeat_dir"], exist_ok=True)
        hb_path = os.path.join(spec["heartbeat_dir"],
                               f"{spec['hid']}.heartbeat")
    host = FleetHost(
        fleet, hid=spec["hid"], port=spec.get("port", 0),
        heartbeat_path=hb_path,
        heartbeat_interval=spec.get("heartbeat_interval", 0.05),
        max_frame_bytes=spec.get("max_frame_bytes", FED_MAX_FRAME_BYTES),
        publish_tick_s=spec.get("publish_tick_s", 0.005))
    print(f"FLEETHOST READY hid={host.hid} port={host.port} "
          f"pid={os.getpid()}", flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        pass
    host.close()
    fleet.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
