"""Asynchronous parameter-server data parallelism.

Reference: deeplearning4j-scaleout-parallelwrapper-parameter-server —
ParameterServerTrainer.java:32,48,68 (after each worker fit:
``parameterServerClient.pushNDArray(model.params())``; pull to resync) and
ParameterServerTrainerContext.java:43,66 (embedded Aeron MediaDriver +
ParameterServerNode).

TPU-native stance (parallel/distributed.py): synchronous ICI collectives
dominate async exchange ON a mesh, so the PS path exists for the topologies
the reference built it for — loosely-coupled hosts. The Aeron UDP transport
becomes HTTP (stdlib) with an in-process fast path; the server is a
thread-safe averaging store (async "staleness" semantics preserved: workers
push whenever they finish a fit, pull before the next one, no barrier).
Optional threshold compression (optimize/accumulation.py) applies on the
push path for bandwidth-poor links.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

import numpy as np


class ParameterServer:
    """In-process parameter store with running-average update semantics
    (reference: ND4J ParameterServerNode's soft-sync behavior: pushed params
    are averaged into the current state)."""

    def __init__(self, initial: np.ndarray, alpha: float = 0.5):
        self._params = np.asarray(initial, np.float32).copy()
        self._alpha = alpha
        self._lock = threading.Lock()
        self.pushes = 0

    def push(self, flat: np.ndarray) -> None:
        with self._lock:
            self._params = ((1.0 - self._alpha) * self._params
                            + self._alpha * np.asarray(flat, np.float32))
            self.pushes += 1

    def pull(self) -> np.ndarray:
        with self._lock:
            return self._params.copy()

    # ------------------------------------------------------------ HTTP front
    def serve(self, port: int = 0) -> int:
        """Expose push/pull over HTTP for multi-host use (Aeron-replacement
        transport)."""
        ps = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_GET(self):
                body = ps.pull().tobytes()
                self.send_response(200)
                self.send_header("Content-Type", "application/octet-stream")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                ps.push(np.frombuffer(self.rfile.read(n), np.float32))
                self.send_response(200)
                self.send_header("Content-Length", "2")
                self.end_headers()
                self.wfile.write(b"ok")

        self._httpd = ThreadingHTTPServer(("127.0.0.1", port), Handler)
        t = threading.Thread(target=self._httpd.serve_forever, daemon=True)
        t.start()
        return self._httpd.server_address[1]

    def stop(self) -> None:
        if getattr(self, "_httpd", None):
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None


class ParameterServerClient:
    """reference: ND4J ParameterServerClient (pushNDArray/getArray) — HTTP or
    direct in-process."""

    def __init__(self, server: Optional[ParameterServer] = None,
                 address: Optional[str] = None):
        if (server is None) == (address is None):
            raise ValueError("Pass exactly one of server / address")
        self.server = server
        self.address = address

    def push(self, flat: np.ndarray) -> None:
        if self.server is not None:
            self.server.push(flat)
            return
        import urllib.request
        req = urllib.request.Request(
            self.address, data=np.asarray(flat, np.float32).tobytes(),
            method="POST")
        urllib.request.urlopen(req, timeout=10).read()

    def pull(self) -> np.ndarray:
        if self.server is not None:
            return self.server.pull()
        import urllib.request
        raw = urllib.request.urlopen(self.address, timeout=10).read()
        return np.frombuffer(raw, np.float32)


class ParameterServerTrainer:
    """Worker-side trainer (reference: ParameterServerTrainer.java:32 —
    fit a batch, push params, pull to resync)."""

    def __init__(self, net, client: ParameterServerClient):
        self.net = net
        self.client = client

    def fit(self, ds) -> None:
        self.net.set_params_flat(self.client.pull())
        self.net.fit(ds)
        self.client.push(self.net.params_flat())


class ParameterServerParallelWrapper:
    """Thread-per-worker async DP (reference:
    ParameterServerParallelWrapperTest's topology: N trainers, one embedded
    server). Each worker owns a replica net; batches round-robin."""

    def __init__(self, net, workers: int = 2, alpha: float = 0.5):
        self.net = net
        self.server = ParameterServer(net.params_flat(), alpha=alpha)
        self.replicas = [net.clone() for _ in range(workers)]
        self.trainers = [
            ParameterServerTrainer(r, ParameterServerClient(self.server))
            for r in self.replicas]

    def fit(self, iterator, epochs: int = 1):
        for _ in range(epochs):
            if hasattr(iterator, "reset"):
                iterator.reset()
            threads = []
            batches = list(iterator)
            per = [batches[i::len(self.trainers)]
                   for i in range(len(self.trainers))]

            def work(trainer, mine):
                for ds in mine:
                    trainer.fit(ds)

            for t, mine in zip(self.trainers, per):
                th = threading.Thread(target=work, args=(t, mine))
                th.start()
                threads.append(th)
            for th in threads:
                th.join()
        self.net.set_params_flat(self.server.pull())
        return self.net
