"""Asynchronous parameter-server data parallelism.

Reference: deeplearning4j-scaleout-parallelwrapper-parameter-server —
ParameterServerTrainer.java:32,48,68 (after each worker fit:
``parameterServerClient.pushNDArray(model.params())``; pull to resync) and
ParameterServerTrainerContext.java:43,66 (embedded Aeron MediaDriver +
ParameterServerNode).

TPU-native stance (parallel/distributed.py): synchronous ICI collectives
dominate async exchange ON a mesh, so the PS path exists for the topologies
the reference built it for — loosely-coupled hosts. The Aeron UDP transport
becomes HTTP (stdlib) with an in-process fast path; the server is a
thread-safe averaging store (async "staleness" semantics preserved: workers
push whenever they finish a fit, pull before the next one, no barrier).

Threshold compression (optimize/accumulation.py) IS wired into the push
path: with ``compress=True`` the trainer pushes threshold-quantised sparse
DELTAS (index+sign wire form, error-feedback residual kept worker-side —
reference: EncodingHandler.java:65 encode, :91 broadcast, hooked into the
step at StochasticGradientDescent.java:74) and the server decodes and
applies them; uncompressed mode pushes full param vectors as before.
"""

from __future__ import annotations

import struct
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

import numpy as np

from deeplearning4j_tpu.optimize.accumulation import (
    EncodingHandler,
    sparsify,
    unsparsify,
)


def _pack_sparse(idx: np.ndarray, signs: np.ndarray, threshold: float,
                 size: int) -> bytes:
    """Wire form of a threshold-encoded delta: the ND4J sparse IntArray
    message in spirit (threshold, logical size, nnz indices, sign bits)."""
    return (struct.pack("<fqi", float(threshold), int(size), int(idx.size))
            + np.asarray(idx, np.int32).tobytes()
            + np.packbits(np.asarray(signs, bool)).tobytes())


def _unpack_sparse(raw: bytes):
    threshold, size, nnz = struct.unpack_from("<fqi", raw)
    off = struct.calcsize("<fqi")
    idx = np.frombuffer(raw, np.int32, count=nnz, offset=off)
    off += 4 * nnz
    signs = np.unpackbits(
        np.frombuffer(raw, np.uint8, offset=off))[:nnz].astype(bool)
    return idx, signs, threshold, size


class ParameterServer:
    """In-process parameter store with running-average update semantics
    (reference: ND4J ParameterServerNode's soft-sync behavior: pushed params
    are averaged into the current state)."""

    def __init__(self, initial: np.ndarray, alpha: float = 0.5):
        self._params = np.asarray(initial, np.float32).copy()
        self._alpha = alpha
        self._lock = threading.Lock()
        self.pushes = 0

    def push(self, flat: np.ndarray) -> None:
        with self._lock:
            self._params = ((1.0 - self._alpha) * self._params
                            + self._alpha * np.asarray(flat, np.float32))
            self.pushes += 1

    def push_sparse_delta(self, idx: np.ndarray, signs: np.ndarray,
                          threshold: float) -> None:
        """Apply a threshold-encoded delta: params[idx] += ±threshold
        (reference: the decode side of EncodingHandler's broadcast — each
        quantised entry is a signed threshold step)."""
        with self._lock:
            np.add.at(self._params, idx,
                      np.where(signs, threshold, -threshold))
            self.pushes += 1

    def pull(self) -> np.ndarray:
        with self._lock:
            return self._params.copy()

    # ------------------------------------------------------------ HTTP front
    def serve(self, port: int = 0) -> int:
        """Expose push/pull over HTTP for multi-host use (Aeron-replacement
        transport)."""
        ps = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_GET(self):
                body = ps.pull().tobytes()
                self.send_response(200)
                self.send_header("Content-Type", "application/octet-stream")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                raw = self.rfile.read(n)
                if self.path.rstrip("/").endswith("delta"):
                    idx, signs, threshold, _ = _unpack_sparse(raw)
                    ps.push_sparse_delta(idx, signs, threshold)
                else:
                    ps.push(np.frombuffer(raw, np.float32))
                self.send_response(200)
                self.send_header("Content-Length", "2")
                self.end_headers()
                self.wfile.write(b"ok")

        self._httpd = ThreadingHTTPServer(("127.0.0.1", port), Handler)
        t = threading.Thread(target=self._httpd.serve_forever, daemon=True)
        t.start()
        return self._httpd.server_address[1]

    def stop(self) -> None:
        if getattr(self, "_httpd", None):
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None


class ParameterServerClient:
    """reference: ND4J ParameterServerClient (pushNDArray/getArray) — HTTP or
    direct in-process."""

    def __init__(self, server: Optional[ParameterServer] = None,
                 address: Optional[str] = None):
        if (server is None) == (address is None):
            raise ValueError("Pass exactly one of server / address")
        self.server = server
        self.address = address

    def push(self, flat: np.ndarray) -> None:
        if self.server is not None:
            self.server.push(flat)
            return
        import urllib.request
        req = urllib.request.Request(
            self.address, data=np.asarray(flat, np.float32).tobytes(),
            method="POST")
        urllib.request.urlopen(req, timeout=10).read()

    def push_sparse_delta(self, idx, signs, threshold: float,
                          size: int) -> None:
        if self.server is not None:
            self.server.push_sparse_delta(np.asarray(idx),
                                          np.asarray(signs), threshold)
            return
        import urllib.request
        req = urllib.request.Request(
            self.address.rstrip("/") + "/delta",
            data=_pack_sparse(np.asarray(idx), np.asarray(signs), threshold,
                              size),
            method="POST")
        urllib.request.urlopen(req, timeout=10).read()

    def pull(self) -> np.ndarray:
        if self.server is not None:
            return self.server.pull()
        import urllib.request
        raw = urllib.request.urlopen(self.address, timeout=10).read()
        return np.frombuffer(raw, np.float32)


class ParameterServerTrainer:
    """Worker-side trainer (reference: ParameterServerTrainer.java:32 —
    fit a batch, push params, pull to resync).

    compress=True switches the push to threshold-encoded sparse DELTAS with
    an error-feedback residual (reference: EncodingHandler.java:65 encode +
    :91 broadcast): after the local fit, delta = params_after - params_pulled
    (+ residual) is quantised to ±threshold at entries over threshold, the
    sparse (idx, sign) message goes over the wire, and the under-threshold
    remainder stays in the residual for the next round. ``message_density``
    records nnz/size per push."""

    def __init__(self, net, client: ParameterServerClient,
                 compress: bool = False, threshold: float = 1e-3):
        self.net = net
        self.client = client
        self.compress = compress
        self.threshold = threshold
        self._encoder = EncodingHandler(threshold)
        self.message_density: list = []

    def fit(self, ds) -> None:
        pulled = self.client.pull()
        self.net.set_params_flat(pulled)
        self.net.fit(ds)
        after = self.net.params_flat()
        if not self.compress:
            self.client.push(after)
            return
        msg = np.asarray(self._encoder.encode(after - pulled))
        idx, signs = sparsify(msg, self.threshold)
        self.message_density.append(idx.size / max(msg.size, 1))
        self.client.push_sparse_delta(idx, signs, self.threshold, msg.size)


class ParameterServerParallelWrapper:
    """Thread-per-worker async DP (reference:
    ParameterServerParallelWrapperTest's topology: N trainers, one embedded
    server). Each worker owns a replica net; batches round-robin."""

    def __init__(self, net, workers: int = 2, alpha: float = 0.5,
                 compress: bool = False, threshold: float = 1e-3):
        self.net = net
        self.server = ParameterServer(net.params_flat(), alpha=alpha)
        self.replicas = [net.clone() for _ in range(workers)]
        self.trainers = [
            ParameterServerTrainer(r, ParameterServerClient(self.server),
                                   compress=compress, threshold=threshold)
            for r in self.replicas]

    def fit(self, iterator, epochs: int = 1):
        for _ in range(epochs):
            if hasattr(iterator, "reset"):
                iterator.reset()
            threads = []
            batches = list(iterator)
            per = [batches[i::len(self.trainers)]
                   for i in range(len(self.trainers))]

            def work(trainer, mine):
                for ds in mine:
                    trainer.fit(ds)

            for t, mine in zip(self.trainers, per):
                th = threading.Thread(target=work, args=(t, mine))
                th.start()
                threads.append(th)
            for th in threads:
                th.join()
        self.net.set_params_flat(self.server.pull())
        return self.net
