"""Tensor-parallel training of real networks via GSPMD sharding.

Beyond reference parity (SURVEY §2.4 checklist: "TP: absent" in DL4J;
the charter requires it as an idiomatic TPU extension). Design: instead of
rewriting layer math shard_map-style, the NETWORK'S OWN jitted train step
(nn/multilayer.py _make_step / nn/graph.py equivalent) is compiled against
parameters placed with per-layer ``NamedSharding``s on a (data, model) mesh
and batches sharded over ``data`` — XLA's SPMD partitioner inserts the
collectives (the "pick a mesh, annotate shardings, let the compiler do the
rest" recipe). The math is bit-identical to the single-device program up to
float reduction order, which is what makes the dp x tp == single-device
parity test possible.

Sharding rules (gated on divisibility by the model-axis size; anything
indivisible stays replicated):

- kernels (ndim >= 2): output axis (last) sharded -> Megatron column style;
  activations come out channel-sharded and the next layer consumes them.
- embedding tables ([V, D] used via take(axis=0), layers named
  EmbeddingLayer): VOCAB rows sharded (axis 0) — each device owns a slice
  of the vocabulary.
- biases / per-channel scales (ndim == 1): sharded to match the kernel's
  output-channel sharding.
- updater state: mirrors the param tree's shardings (Adam m/v etc. are
  zeros_like(params) trees — see nn/updater.py).
- layer state (BN running stats, ...): replicated — small, and replication
  keeps every case correct.
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS

__all__ = ["network_param_specs", "shard_network", "ShardedTrainer",
           "data_batch_sharding"]


def _leaf_spec(arr, model_size: int, *, embedding: bool,
               expert: bool = False) -> P:
    shape = np.shape(arr)
    if len(shape) == 0:
        return P()
    if embedding and len(shape) == 2 and shape[0] % model_size == 0:
        return P(MODEL_AXIS, None)  # vocab-row sharding
    if expert and len(shape) >= 2 and shape[0] % model_size == 0:
        # stacked-expert tensors [E, ...]: shard the EXPERT axis — each
        # device owns E/m experts (expert parallelism); XLA partitions the
        # per-expert einsums and reduces the gate-combine over ICI
        return P(*([MODEL_AXIS] + [None] * (len(shape) - 1)))
    if shape[-1] % model_size == 0 and shape[-1] >= model_size:
        return P(*([None] * (len(shape) - 1) + [MODEL_AXIS]))
    return P()


def _layer_of(net, key: str):
    """The layer object behind a param-tree top key, for MLN (int index keys)
    and ComputationGraph (vertex-name keys with .layer), else None."""
    layers = getattr(net, "layers", None)
    if isinstance(layers, list) and key.isdigit() and int(key) < len(layers):
        return layers[int(key)]
    vertices = getattr(getattr(net, "conf", None), "vertices", None)
    if isinstance(vertices, dict) and key in vertices:
        return getattr(vertices[key], "layer", vertices[key])
    return None


def network_param_specs(net, model_size: int) -> dict:
    """PartitionSpec tree matching ``net.params`` under the rules above."""
    specs = {}
    for key, sub in net.params.items():
        layer = _layer_of(net, key)
        is_emb = type(layer).__name__ == "EmbeddingLayer"
        is_moe = type(layer).__name__ == "MixtureOfExpertsLayer"
        specs[key] = {
            name: _leaf_spec(arr, model_size, embedding=is_emb,
                             expert=is_moe and name != "Wg")
            for name, arr in sub.items()}
    return specs


def data_batch_sharding(mesh: Mesh, arr) -> NamedSharding:
    """Batch (axis 0) sharded over ``data``, rest replicated."""
    nd = np.ndim(arr)
    return NamedSharding(mesh, P(*([DATA_AXIS] + [None] * (nd - 1))))


def shard_network(net, mesh: Mesh) -> dict:
    """Place net.params / updater_state / state on the mesh (params +
    updater state per-layer sharded, layer state replicated). Returns the
    param spec tree."""
    m = mesh.shape[MODEL_AXIS]
    pspecs = network_param_specs(net, m)
    put = jax.tree_util.tree_map(
        lambda a, sp: jax.device_put(a, NamedSharding(mesh, sp)),
        net.params, pspecs)
    net.params = put
    ptree = jax.tree_util.tree_structure(net.params)
    new_us = {}
    for key, sub in net.updater_state.items():
        if jax.tree_util.tree_structure(sub) == ptree:
            new_us[key] = jax.tree_util.tree_map(
                lambda a, sp: jax.device_put(a, NamedSharding(mesh, sp)),
                sub, pspecs)
        else:
            new_us[key] = jax.device_put(sub, NamedSharding(mesh, P()))
    net.updater_state = new_us
    net.state = jax.device_put(net.state, NamedSharding(mesh, P()))
    return pspecs


class _PlacedIterator:
    """Wraps a DataSetIterator, yielding mesh-placed batches."""

    def __init__(self, it, place):
        self._it = it
        self._place = place

    def __iter__(self):
        return (self._place(ds) for ds in self._it)

    def reset(self):
        if hasattr(self._it, "reset"):
            self._it.reset()


class ShardedTrainer:
    """dp x tp trainer: any net with the ``do_step`` contract trains with
    parameters tensor-sharded over ``model`` and batches sharded over
    ``data``. The per-device batch is batch_size / mesh.shape['data'];
    batch_size must divide evenly (static shapes keep one XLA program).

    >>> mesh = data_model_mesh(4, 2)
    >>> trainer = ShardedTrainer(net, mesh)
    >>> trainer.fit(iterator, epochs=2)
    """

    def __init__(self, net, mesh: Mesh):
        if DATA_AXIS not in mesh.shape or MODEL_AXIS not in mesh.shape:
            raise ValueError(
                f"mesh must have ({DATA_AXIS}, {MODEL_AXIS}) axes, got "
                f"{dict(mesh.shape)}")
        self.net = net
        self.mesh = mesh
        self.param_specs = shard_network(net, mesh)

    def _place_ds(self, ds):
        d = self.mesh.shape[DATA_AXIS]
        feats = np.asarray(ds.features)
        if feats.shape[0] % d != 0:
            raise ValueError(
                f"batch size {feats.shape[0]} not divisible by data-axis "
                f"size {d}")
        out = []
        for a in (feats, ds.labels, ds.features_mask, ds.labels_mask):
            if a is None:
                out.append(None)
                continue
            a = np.asarray(a)
            out.append(jax.device_put(a, data_batch_sharding(self.mesh, a)))
        return DataSet.on_device(*out)

    def fit(self, iterator, epochs: int = 1):
        """Delegates to the net's own fit (listeners, epochs, TBPTT routing
        all apply); this wrapper only places each minibatch data-sharded on
        the mesh before the step sees it."""
        if isinstance(iterator, DataSet):
            return self.net.fit(self._place_ds(iterator), epochs=epochs)
        return self.net.fit(_PlacedIterator(iterator, self._place_ds),
                            epochs=epochs)

    def output(self, x):
        """Sharded inference: batch over data, params stay tensor-sharded."""
        x = np.asarray(x)
        return self.net.output(
            jax.device_put(x, data_batch_sharding(self.mesh, x)))
