"""KV-page snapshot/restore: crash-durable generation state handoff.

The paged decode path (parallel/generation.py) keeps every bit of a
live request's restartable state in host mirrors plus device KV pages:
the prompt, the accepted-token history, the stream position, and the
sampling params. Because the sampling key schedule is server-state-free
(``fold_in(PRNGKey(seed), token_index)``), that state is sufficient to
resume the request anywhere and reproduce the remaining completion
bit-for-bit. This module gives that state a wire format:

- ``KVSnapshot`` — a versioned, checksummed serialization of one live
  slot: resident KV pages (stacked per attention layer, int8 pages ship
  with their scale planes and are ~3.55x smaller than f32), the logical
  page list with the prefix-cache chunk digests attached, and the resume
  header (prompt, emitted tokens, position, fold-in count, sampling
  params). ``to_bytes()``/``from_bytes()`` round-trip it through a flat
  byte string; ``verify()`` recomputes the sha256 over the content so a
  corrupted snapshot is detected *before* any page lands in a pool.
- Prefix dedup both ways: pages whose content is a registered prefix
  chunk carry their chained digest, so an adopting server that already
  holds the chunk shares the resident page instead of uploading the
  payload copy, and uploaded prompt pages are re-registered into the
  adopter's prefix cache — shared prefixes re-dedupe on arrival.
- ``export_request(server, future)`` / ``adopt_request(server, snap)``
  — module-level verbs over ``GenerationServer.export_request`` /
  ``GenerationServer.adopt_request``.

Consumers: ``GenerationServer`` (periodic ``snapshot_every``
snapshotting, preemption resume, ``drain(migrate=...)``) and
``ReplicaFleet`` (mid-stream failover resumes from the newest valid
snapshot instead of regenerating from token 0).

Snapshots are model-blind: adopting a snapshot into a server whose net
holds different weights resumes *consistently but meaninglessly* (the
KV pages encode the exporter's weights). The fleet use — replicas built
by one factory over shared weights — satisfies this by construction.
"""

from __future__ import annotations

import hashlib
import json
import struct
from typing import Dict, List, Optional

import numpy as np

from deeplearning4j_tpu.parallel.resilience import ResilienceError

#: KVSnapshot wire-format version. Bump on any layout change. Unknown
#: versions are refused typed ``SnapshotInvalid``; KNOWN-but-different
#: versions (a v3 snapshot at a v2-geometry reader, or vice versa) are
#: refused typed ``SnapshotUnsupported`` with the full geometry tuple in
#: the message — never a checksum error, never a silent truncation.
#: v2: ``deadline_remaining`` joined the resume header — the request's
#: remaining Deadline budget in seconds (never an absolute timestamp, so
#: the field survives wall-clock skew between exporter and adopter).
#: v3: mesh-aware page geometry — ``shards`` (the exporter's
#: tensor-parallel degree) and ``head_layout`` joined the header. The
#: page payload is ALWAYS the canonical host layout (full
#: ``[NP, H, ps, d]`` stacks — export gathers the head shards back
#: together), so any-tp adopters re-shard locally and a tp=2 exporter
#: hands off to a tp=4 or tp=1 adopter without a re-pack.
WIRE_VERSION = 3

#: the one payload layout v3 speaks: full head axis, page-major. Kept as
#: a named constant so a future device-native layout bumps the wire
#: version instead of silently reinterpreting bytes.
CANONICAL_HEAD_LAYOUT = "canonical"

_MAGIC = b"KVSN"


class SnapshotError(ResilienceError):
    """Base of the handoff failure taxonomy. Every snapshot/adopt
    failure is typed so the fleet can fall back to token-0 regeneration
    instead of losing the request."""


class SnapshotInvalid(SnapshotError):
    """The snapshot failed checksum or version validation — corrupted
    in transit or produced by an incompatible writer. Never adopted;
    the caller regenerates from token 0."""


class SnapshotUnsupported(SnapshotError):
    """The snapshot cannot be hosted by this server (kv_dtype/page
    geometry mismatch, or a speculative-decoding server on either
    end — the draft's dense cache is not part of the wire format)."""


class SnapshotUnavailable(SnapshotError):
    """No snapshot could be taken: the request is not (or no longer)
    resident in a decode slot."""


class RequestMigrated(ResilienceError):
    """The request was exported off a draining server mid-stream. The
    snapshot rides on the failed future (``_kv_snapshot``); a fleet
    parks the request and resumes it on another replica. HTTP mapping:
    503 (when it escapes a bare server with no fleet above it)."""


def _leaf_items(payload: Dict[str, Dict[str, np.ndarray]]):
    """Deterministic (vertex, leaf, array) iteration order — the
    checksum and the byte layout both depend on it."""
    for vn in sorted(payload):
        for leaf in sorted(payload[vn]):
            yield vn, leaf, payload[vn][leaf]


class KVSnapshot:
    """One live generation request, serialized. Header fields are plain
    Python scalars; ``payload`` stacks the resident pages per attention
    vertex as ``{vertex: {leaf: [n_pages, ...] array}}`` (int8 pools
    carry ``kscales``/``vscales`` planes alongside ``kpages``/
    ``vpages``); ``page_digests[i]`` is the prefix-cache chunk digest of
    logical page ``i`` when the exporter had it registered, else None.
    """

    __slots__ = ("version", "prompt", "tokens", "pos", "count", "last",
                 "key", "temperature", "top_k", "seed", "eos_id",
                 "max_tokens", "kv_dtype", "page_size",
                 "page_token_bytes", "page_digests", "payload",
                 "deadline_remaining", "shards", "head_layout",
                 "checksum")

    def __init__(self, *, version, prompt, tokens, pos, count, last, key,
                 temperature, top_k, seed, eos_id, max_tokens, kv_dtype,
                 page_size, page_token_bytes, page_digests, payload,
                 deadline_remaining=None, shards=1,
                 head_layout=CANONICAL_HEAD_LAYOUT, checksum=None):
        self.version = int(version)
        self.prompt = np.asarray(prompt, np.int64)
        self.tokens = [int(t) for t in tokens]
        self.pos = int(pos)
        self.count = int(count)
        self.last = int(last)
        self.key = np.asarray(key, np.uint32)
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.seed = int(seed)
        self.eos_id = None if eos_id is None else int(eos_id)
        self.max_tokens = int(max_tokens)
        self.kv_dtype = kv_dtype
        self.page_size = int(page_size)
        self.page_token_bytes = int(page_token_bytes)
        self.page_digests: List[Optional[bytes]] = list(page_digests)
        self.payload = payload
        #: remaining request Deadline budget (seconds) at pack time — a
        #: duration, not a timestamp, so adoption on another host with a
        #: skewed wall clock re-arms the same budget (monotonic-deadline
        #: rule). None = the request carried no deadline.
        self.deadline_remaining = None if deadline_remaining is None \
            else float(deadline_remaining)
        #: v3 mesh-aware page geometry: how many head shards the
        #: EXPORTING server decoded over (diagnostics — the payload is
        #: canonical regardless) and the payload's head-axis layout.
        #: A version-2 snapshot keeps the implied single-chip values.
        self.shards = int(shards)
        self.head_layout = str(head_layout)
        self.checksum = checksum if checksum is not None \
            else self.content_digest()

    # ------------------------------------------------------ integrity
    def _header(self) -> dict:
        # the sharded-geometry fields join the header at v3 ONLY: a
        # version-2 snapshot built by this writer (downgrade_snapshot)
        # stays byte-identical — header, checksum and framing — to one
        # a pre-v3 writer would emit, which is what keeps the v2 adopt
        # fallback honest
        hdr = {
            "version": self.version,
            "prompt": self.prompt.tolist(),
            "tokens": self.tokens,
            "pos": self.pos,
            "count": self.count,
            "last": self.last,
            "key": self.key.tolist(),
            "temperature": self.temperature,
            "top_k": self.top_k,
            "seed": self.seed,
            "eos_id": self.eos_id,
            "max_tokens": self.max_tokens,
            "kv_dtype": self.kv_dtype,
            "page_size": self.page_size,
            "page_token_bytes": self.page_token_bytes,
            "deadline_remaining": self.deadline_remaining,
            "page_digests": [None if d is None else d.hex()
                             for d in self.page_digests],
            "leaves": [[vn, leaf, str(a.dtype), list(a.shape)]
                       for vn, leaf, a in _leaf_items(self.payload)],
        }
        if self.version >= 3:
            hdr["shards"] = self.shards
            hdr["head_layout"] = self.head_layout
        return hdr

    def content_digest(self) -> bytes:
        """sha256 over the canonical header AND every payload byte —
        a single flipped bit anywhere fails ``verify()``."""
        h = hashlib.sha256()
        h.update(_MAGIC)
        h.update(json.dumps(self._header(), sort_keys=True).encode())
        for _vn, _leaf, a in _leaf_items(self.payload):
            h.update(np.ascontiguousarray(a).tobytes())
        return h.digest()

    def verify(self) -> bool:
        return self.checksum == self.content_digest()

    @property
    def n_pages(self) -> int:
        return len(self.page_digests)

    def wire_bytes(self) -> int:
        """Size of the serialized snapshot — the ``handoff_bytes``
        accounting (int8 KV shows up here as the ~3.55x shrink)."""
        header = json.dumps(self._header(), sort_keys=True).encode()
        n = len(_MAGIC) + 2 + 4 + len(header) + len(self.checksum)
        for _vn, _leaf, a in _leaf_items(self.payload):
            n += a.nbytes
        return n

    # -------------------------------------------------- serialization
    def to_bytes(self) -> bytes:
        header = json.dumps(self._header(), sort_keys=True).encode()
        parts = [_MAGIC, struct.pack("<HI", self.version, len(header)),
                 header]
        for _vn, _leaf, a in _leaf_items(self.payload):
            parts.append(np.ascontiguousarray(a).tobytes())
        parts.append(self.checksum)
        return b"".join(parts)

    #: wire versions this reader can PARSE (framing + header keys).
    #: Parseable is weaker than adoptable: a cross-version read is
    #: refused typed AFTER the header parse, so the refusal can name the
    #: full geometry tuple instead of degenerating into a checksum error.
    KNOWN_VERSIONS = (2, 3)

    @classmethod
    def from_bytes(cls, blob: bytes, *,
                   supported: int = WIRE_VERSION) -> "KVSnapshot":
        """Deserialize one snapshot. ``supported`` is the reader's own
        wire generation (a v2-geometry decode tier passes 2): a KNOWN
        version that differs from it fails typed ``SnapshotUnsupported``
        with the geometry tuple (version/shards/head_layout/kv_dtype/
        page geometry) in the message — never a checksum error, never a
        silent truncation — while an UNKNOWN version fails
        ``SnapshotInvalid`` before any parsing is trusted."""
        if len(blob) < len(_MAGIC) + 6 or not blob.startswith(_MAGIC):
            raise SnapshotInvalid("not a KVSnapshot byte stream")
        off = len(_MAGIC)
        version, hlen = struct.unpack_from("<HI", blob, off)
        if version not in cls.KNOWN_VERSIONS:
            raise SnapshotInvalid(
                f"KVSnapshot wire version {version} != supported "
                f"{supported}")
        off += 6
        try:
            hdr = json.loads(blob[off:off + hlen].decode())
        except Exception as e:
            raise SnapshotInvalid(f"unreadable snapshot header: {e}")
        if version != supported:
            raise SnapshotUnsupported(
                "cross-version KVSnapshot refused before adoption: "
                f"geometry (version={version}, "
                f"shards={hdr.get('shards', 1)}, "
                f"head_layout={hdr.get('head_layout', CANONICAL_HEAD_LAYOUT)!r}, "
                f"kv_dtype={hdr.get('kv_dtype')!r}, "
                f"page_size={hdr.get('page_size')}, "
                f"page_token_bytes={hdr.get('page_token_bytes')}) from a "
                f"v{version} writer at a v{supported}-geometry reader")
        off += hlen
        payload: Dict[str, Dict[str, np.ndarray]] = {}
        for vn, leaf, dtype, shape in hdr["leaves"]:
            a = np.frombuffer(
                blob, dtype=np.dtype(dtype), offset=off,
                count=int(np.prod(shape, dtype=np.int64))
            ).reshape(shape).copy()
            payload.setdefault(vn, {})[leaf] = a
            off += a.nbytes
        checksum = blob[off:off + 32]
        snap = cls(
            version=version, prompt=hdr["prompt"], tokens=hdr["tokens"],
            pos=hdr["pos"], count=hdr["count"], last=hdr["last"],
            key=hdr["key"], temperature=hdr["temperature"],
            top_k=hdr["top_k"], seed=hdr["seed"], eos_id=hdr["eos_id"],
            max_tokens=hdr["max_tokens"], kv_dtype=hdr["kv_dtype"],
            page_size=hdr["page_size"],
            page_token_bytes=hdr["page_token_bytes"],
            page_digests=[None if d is None else bytes.fromhex(d)
                          for d in hdr["page_digests"]],
            payload=payload,
            deadline_remaining=hdr["deadline_remaining"],
            shards=hdr.get("shards", 1),
            head_layout=hdr.get("head_layout", CANONICAL_HEAD_LAYOUT),
            checksum=checksum)
        if not snap.verify():
            raise SnapshotInvalid("KVSnapshot checksum mismatch")
        return snap


def peek_snapshot(blob: bytes) -> dict:
    """Parse ONLY the wire framing and JSON header of a serialized
    snapshot — no payload copy, no checksum pass — for routers (the
    fleet federation) that ship snapshots as opaque bytes but need the
    stream position to order competing harvests. Returns a dict with
    ``version``, ``count``, ``pos``, ``tokens`` (generated so far),
    ``deadline_remaining`` and ``wire_bytes``. A malformed prefix fails
    typed ``SnapshotInvalid``; a KNOWN-but-foreign version still peeks
    fine (the refusal decision belongs to the adopting host, which runs
    the full ``from_bytes`` geometry check)."""
    if len(blob) < len(_MAGIC) + 6 or not blob.startswith(_MAGIC):
        raise SnapshotInvalid("not a KVSnapshot byte stream")
    off = len(_MAGIC)
    version, hlen = struct.unpack_from("<HI", blob, off)
    if version not in KVSnapshot.KNOWN_VERSIONS:
        raise SnapshotInvalid(
            f"KVSnapshot wire version {version} is unknown to this "
            "reader")
    off += 6
    if hlen > len(blob) - off:
        raise SnapshotInvalid(
            f"snapshot header length {hlen} exceeds the {len(blob)}-byte "
            "blob — truncated or corrupt framing")
    try:
        hdr = json.loads(blob[off:off + hlen].decode())
    except Exception as e:
        raise SnapshotInvalid(f"unreadable snapshot header: {e}")
    return {"version": version,
            "count": hdr.get("count", 0),
            "pos": hdr.get("pos", 0),
            "tokens": len(hdr.get("tokens", ())),
            "deadline_remaining": hdr.get("deadline_remaining"),
            "wire_bytes": len(blob)}


def pack_snapshot(*, req, pos, count, last, key, kv_dtype, page_size,
                  page_token_bytes, page_digests, fetched, n_pages,
                  shards=1,
                  head_layout=CANONICAL_HEAD_LAYOUT) -> KVSnapshot:
    """Assemble a ``KVSnapshot`` from the server's host mirrors plus one
    fetched page stack. ``fetched`` is the block-table-width device
    fetch ``{vertex: {leaf: [NP, ...]}}``; only the first ``n_pages``
    rows hold this slot's resident KV. Every host conversion (int casts,
    list copies, array slices) happens HERE, outside the serving loop's
    hot-named functions. The request's remaining Deadline budget is
    captured as a duration so the adopter re-arms the same clock."""
    n = int(n_pages)
    payload = {vn: {leaf: np.ascontiguousarray(a[:n])
                    for leaf, a in leaves.items()}
               for vn, leaves in fetched.items()}
    deadline = getattr(req, "deadline", None)
    remaining = None if deadline is None else max(0.0,
                                                 deadline.remaining())
    return KVSnapshot(
        version=WIRE_VERSION, prompt=req.prompt, tokens=list(req.tokens),
        pos=pos, count=count, last=last, key=key,
        temperature=req.temperature, top_k=req.top_k, seed=req.seed,
        eos_id=req.eos_id, max_tokens=req.max_tokens, kv_dtype=kv_dtype,
        page_size=page_size, page_token_bytes=page_token_bytes,
        page_digests=list(page_digests)[:n], payload=payload,
        deadline_remaining=remaining, shards=shards,
        head_layout=head_layout)


def downgrade_snapshot(snap: KVSnapshot) -> KVSnapshot:
    """Re-emit a v3 snapshot as wire v2 — byte-identical (header,
    framing, checksum) to what a pre-v3 writer would have produced for
    the same request, which is possible precisely because the v3 payload
    layout IS the v2 layout (canonical host stacks). The bridge for
    shipping to a fleet tier still running v2-geometry readers; refuses
    a non-canonical layout loudly rather than emit bytes a v2 reader
    would misinterpret."""
    if snap.head_layout != CANONICAL_HEAD_LAYOUT:
        raise SnapshotUnsupported(
            f"cannot downgrade a {snap.head_layout!r}-layout snapshot "
            "to wire v2: v2 readers only speak the canonical host "
            "layout")
    return KVSnapshot(
        version=2, prompt=snap.prompt, tokens=list(snap.tokens),
        pos=snap.pos, count=snap.count, last=snap.last, key=snap.key,
        temperature=snap.temperature, top_k=snap.top_k, seed=snap.seed,
        eos_id=snap.eos_id, max_tokens=snap.max_tokens,
        kv_dtype=snap.kv_dtype, page_size=snap.page_size,
        page_token_bytes=snap.page_token_bytes,
        page_digests=list(snap.page_digests), payload=snap.payload,
        deadline_remaining=snap.deadline_remaining)


def padded_payload(snap: KVSnapshot, np_pages: int
                   ) -> Dict[str, Dict[str, np.ndarray]]:
    """Zero-pad the snapshot's ``[n, ...]`` page stacks to the adopting
    server's block-table width ``[NP, ...]`` so the one compiled store
    program fits every adopt (pad rows are routed to the garbage page)."""
    out: Dict[str, Dict[str, np.ndarray]] = {}
    for vn, leaves in snap.payload.items():
        out[vn] = {}
        for leaf, a in leaves.items():
            padded = np.zeros((np_pages,) + a.shape[1:], a.dtype)
            padded[:a.shape[0]] = a
            out[vn][leaf] = padded
    return out


def corrupt_snapshot(snap: KVSnapshot) -> KVSnapshot:
    """Flip one payload bit *after* the checksum was computed — the
    chaos injector's ``snapshot_corrupt`` mode and the test hook for the
    checksum-fallback path. Returns the same (now invalid) snapshot."""
    for vn, leaf, a in _leaf_items(snap.payload):
        if a.size:
            # leaves off device transfers / frombuffer are read-only:
            # mutate a copy and swap it into the payload tree
            b = np.array(a)
            flat = b.view(np.uint8).reshape(-1)
            flat[0] ^= 0xFF
            snap.payload[vn][leaf] = b
            return snap
    # pathological empty payload: break the checksum directly
    snap.checksum = bytes(32)
    return snap


def truncate_snapshot(snap: KVSnapshot) -> KVSnapshot:
    """Zero the tail half of the last payload leaf *after* the checksum
    was computed — the chaos injector's ``handoff_truncate`` mode: the
    wire analog of a transfer cut short, where the missing tail reads
    back as zeros and the adopter's ``verify()`` fails before any page
    lands in its pool. Returns the same (now invalid) snapshot."""
    last_leaf = None
    for vn, leaf, a in _leaf_items(snap.payload):
        if a.size:
            last_leaf = (vn, leaf, a)
    if last_leaf is None:
        snap.checksum = bytes(32)
        return snap
    vn, leaf, a = last_leaf
    b = np.array(a)  # device fetches / frombuffer views are read-only
    flat = b.view(np.uint8).reshape(-1)
    flat[flat.size // 2:] = 0
    if np.array_equal(b, a):
        flat[-1] ^= 0xFF  # tail was already zeros: still break content
    snap.payload[vn][leaf] = b
    return snap


def export_request(server, future, timeout: Optional[float] = 30.0
                   ) -> KVSnapshot:
    """Snapshot the live request behind ``future`` on ``server`` (a
    ``GenerationServer``). Raises ``SnapshotUnavailable`` when the
    request is not resident in a slot."""
    return server.export_request(future, timeout=timeout)


def adopt_request(server, snapshot: KVSnapshot, **kwargs):
    """Adopt ``snapshot`` into a free slot of ``server`` and resume
    decoding at position N. Returns the Future of the resumed request;
    its result is byte-identical to the never-interrupted completion."""
    return server.adopt_request(snapshot, **kwargs)
