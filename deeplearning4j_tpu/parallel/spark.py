"""Cluster-style training facade: TrainingMaster + Spark-like wrappers.

Reference: dl4j-spark — TrainingMaster SPI (spark/api/TrainingMaster.java:29),
ParameterAveragingTrainingMaster.java:367-490 (executeTraining: split RDD,
broadcast NetBroadcastTuple, per-worker minibatch loops, treeAggregate then
params/updater divided by count), SparkDl4jMultiLayer / SparkComputationGraph
(impl/multilayer/SparkDl4jMultiLayer.java, distributed eval :443-540).

TPU-native mapping: the "cluster" is the device mesh; an RDD of DataSets is a
host-side list/iterator that gets partitioned into per-round worker groups;
"broadcast + treeAggregate-average" IS one ParallelWrapper averaging round
(lax.pmean over ICI). averaging_frequency maps to the reference's
batchSizePerWorker * averagingFrequency semantics; rdd_data_set_num_examples
and workers_per_node collapse into the mesh size. The parity contract ported
from TestCompareParameterAveragingSparkVsSingleMachine holds: with
averaging_frequency=1 this equals single-device training on the concatenated
batches.
"""

from __future__ import annotations

from typing import Optional

from jax.sharding import Mesh

from deeplearning4j_tpu.parallel.evaluation import evaluate_on_mesh
from deeplearning4j_tpu.parallel.mesh import data_mesh
from deeplearning4j_tpu.parallel.trainer import AVERAGING, ParallelWrapper


class TrainingMaster:
    """SPI (reference: spark/api/TrainingMaster.java:29)."""

    def execute_training(self, net, data) -> None:
        raise NotImplementedError


class ParameterAveragingTrainingMaster(TrainingMaster):
    """reference: impl/paramavg/ParameterAveragingTrainingMaster.java —
    builder knobs kept: batch_size_per_worker, averaging_frequency,
    aggregation_depth (accepted; XLA picks the reduction tree on ICI so it is
    a no-op here), repartition strategy (host-side round-robin is the only
    one needed: device feeding is deterministic)."""

    def __init__(self, batch_size_per_worker: int = 16,
                 averaging_frequency: int = 1,
                 aggregation_depth: int = 2,
                 average_updaters: bool = True,
                 mesh: Optional[Mesh] = None,
                 workers: Optional[int] = None):
        self.batch_size_per_worker = batch_size_per_worker
        self.averaging_frequency = averaging_frequency
        self.aggregation_depth = aggregation_depth
        self.average_updaters = average_updaters
        self.mesh = mesh if mesh is not None else data_mesh(workers)

    def execute_training(self, net, data) -> None:
        """data: iterator/list of DataSets, or one DataSet re-batched to
        batch_size_per_worker (the Export/Direct RDD approaches both reduce
        to this)."""
        from deeplearning4j_tpu.datasets.dataset import DataSet

        if isinstance(data, DataSet):
            data = list(data.batch_by(self.batch_size_per_worker))
        pw = ParallelWrapper(net, mesh=self.mesh, mode=AVERAGING,
                             averaging_frequency=self.averaging_frequency,
                             average_updaters=self.average_updaters)
        pw.fit(data)


class SparkDl4jMultiLayer:
    """reference: impl/multilayer/SparkDl4jMultiLayer.java — net + master
    facade with fit / evaluate."""

    def __init__(self, net, training_master: TrainingMaster):
        self.net = net
        self.master = training_master

    def fit(self, data, epochs: int = 1):
        for _ in range(epochs):
            self.master.execute_training(self.net, data)
        return self.net

    def evaluate(self, iterator, evaluation=None):
        """Distributed (map-reduce) evaluation (reference:
        SparkDl4jMultiLayer.java:443-540 -> IEvaluateFlatMapFunction +
        IEvaluation.merge)."""
        mesh = getattr(self.master, "mesh", None)
        return evaluate_on_mesh(self.net, iterator, mesh=mesh,
                                evaluation=evaluation)

    def get_network(self):
        return self.net


class SparkComputationGraph(SparkDl4jMultiLayer):
    """reference: impl/graph/SparkComputationGraph.java — identical facade;
    ComputationGraph satisfies the same functional contract."""
