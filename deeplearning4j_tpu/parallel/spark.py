"""Cluster-style training facade: TrainingMaster + Spark-like wrappers.

Reference: dl4j-spark — TrainingMaster SPI (spark/api/TrainingMaster.java:29),
ParameterAveragingTrainingMaster.java:367-490 (executeTraining: split RDD,
broadcast NetBroadcastTuple, per-worker minibatch loops, treeAggregate then
params/updater divided by count), SparkDl4jMultiLayer / SparkComputationGraph
(impl/multilayer/SparkDl4jMultiLayer.java, distributed eval :443-540).

TPU-native mapping: the "cluster" is the device mesh; an RDD of DataSets is a
host-side list/iterator that gets partitioned into per-round worker groups;
"broadcast + treeAggregate-average" IS one ParallelWrapper averaging round
(lax.pmean over ICI). averaging_frequency maps to the reference's
batchSizePerWorker * averagingFrequency semantics; rdd_data_set_num_examples
and workers_per_node collapse into the mesh size. The parity contract ported
from TestCompareParameterAveragingSparkVsSingleMachine holds: with
averaging_frequency=1 this equals single-device training on the concatenated
batches.
"""

from __future__ import annotations

import warnings
from typing import Optional

import numpy as np
from jax.sharding import Mesh

from deeplearning4j_tpu.parallel.evaluation import evaluate_on_mesh
from deeplearning4j_tpu.parallel.mesh import data_mesh
from deeplearning4j_tpu.parallel.trainer import AVERAGING, ParallelWrapper

# Repartition strategies (reference: spark/api/Repartition.java — Always /
# Never / NumPartitionsWorkersDiffers; RepartitionStrategy.Balanced)
REPARTITION_ALWAYS = "always"
REPARTITION_NEVER = "never"


def repartition_datasets(data, batch_size: int,
                         strategy: str = REPARTITION_ALWAYS):
    """Balance-if-required (reference:
    SparkUtils.repartitionBalanceIfRequired, the ParameterAveraging
    default path): if the incoming DataSets are already uniform
    minibatches, keep them; otherwise re-split ALL examples into uniform
    ``batch_size`` minibatches. The observable semantics are the ones that
    matter for mesh training: every worker round sees same-shaped batches,
    so XLA compiles ONE program shape and no mid-stream odd batch is
    dropped."""
    if strategy == REPARTITION_NEVER:
        return list(data)
    from deeplearning4j_tpu.datasets.dataset import DataSet

    data = list(data)
    if not data:
        return data
    sizes = {int(np.shape(d.features)[0]) for d in data}
    if len(sizes) == 1:
        return data  # already balanced
    if any(d.features_mask is not None or d.labels_mask is not None
           for d in data):
        # masked (variable-length) data: element moves would need mask
        # re-padding; keep caller batching
        return data
    feats = np.concatenate([np.asarray(d.features) for d in data])
    labels = np.concatenate([np.asarray(d.labels) for d in data])
    n = feats.shape[0]
    out = []
    for s in range(0, n - n % batch_size, batch_size):
        out.append(DataSet(feats[s:s + batch_size],
                           labels[s:s + batch_size]))
    tail = n % batch_size
    if tail:
        out.append(DataSet(feats[n - tail:], labels[n - tail:]))
    return out


class TrainingMaster:
    """SPI (reference: spark/api/TrainingMaster.java:29)."""

    def execute_training(self, net, data) -> None:
        raise NotImplementedError


class ParameterAveragingTrainingMaster(TrainingMaster):
    """reference: impl/paramavg/ParameterAveragingTrainingMaster.java.

    ``repartition``: 'always' means balance-IF-REQUIRED (the reference's
    default path) — uniform incoming minibatches are kept as the round
    unit whatever their size; only RAGGED unmasked data is re-sliced into
    uniform batch_size_per_worker minibatches (masked variable-length data
    is left to caller batching — element moves would need mask
    re-padding). 'never' always trusts caller batching.
    ``aggregation_depth`` (Spark treeAggregate fan-in) cannot have an
    effect: parameter averaging is one ``lax.pmean`` and XLA chooses the
    reduction tree over ICI — passing a non-default value warns rather
    than silently pretending."""

    def __init__(self, batch_size_per_worker: int = 16,
                 averaging_frequency: int = 1,
                 aggregation_depth: int = 2,
                 average_updaters: bool = True,
                 repartition: str = REPARTITION_ALWAYS,
                 mesh: Optional[Mesh] = None,
                 workers: Optional[int] = None):
        self.batch_size_per_worker = batch_size_per_worker
        self.averaging_frequency = averaging_frequency
        if aggregation_depth != 2:
            warnings.warn(
                "aggregation_depth has no effect on a device mesh: "
                "averaging is one XLA pmean and the compiler picks the "
                "reduction tree over ICI", stacklevel=2)
        self.aggregation_depth = aggregation_depth
        self.average_updaters = average_updaters
        if repartition not in (REPARTITION_ALWAYS, REPARTITION_NEVER):
            raise ValueError(f"Unknown repartition '{repartition}'")
        self.repartition = repartition
        self.mesh = mesh if mesh is not None else data_mesh(workers)

    def execute_training(self, net, data) -> None:
        """data: iterator/list of DataSets, or one DataSet re-batched to
        batch_size_per_worker (the Export/Direct RDD approaches both reduce
        to this)."""
        from deeplearning4j_tpu.datasets.dataset import DataSet

        if isinstance(data, DataSet):
            data = list(data.batch_by(self.batch_size_per_worker))
        else:
            data = repartition_datasets(data, self.batch_size_per_worker,
                                        self.repartition)
        pw = ParallelWrapper(net, mesh=self.mesh, mode=AVERAGING,
                             averaging_frequency=self.averaging_frequency,
                             average_updaters=self.average_updaters)
        pw.fit(data)


class SparkDl4jMultiLayer:
    """reference: impl/multilayer/SparkDl4jMultiLayer.java — net + master
    facade with fit / evaluate."""

    def __init__(self, net, training_master: TrainingMaster):
        self.net = net
        self.master = training_master

    def fit(self, data, epochs: int = 1):
        for _ in range(epochs):
            self.master.execute_training(self.net, data)
        return self.net

    def evaluate(self, iterator, evaluation=None):
        """Distributed (map-reduce) evaluation (reference:
        SparkDl4jMultiLayer.java:443-540 -> IEvaluateFlatMapFunction +
        IEvaluation.merge)."""
        mesh = getattr(self.master, "mesh", None)
        return evaluate_on_mesh(self.net, iterator, mesh=mesh,
                                evaluation=evaluation)

    def get_network(self):
        return self.net


class SparkComputationGraph(SparkDl4jMultiLayer):
    """reference: impl/graph/SparkComputationGraph.java — identical facade;
    ComputationGraph satisfies the same functional contract."""
