"""Failure detection and elastic recovery for long training runs.

The reference has essentially no failure handling (SURVEY §5: Spark-level
RDD-lineage retry only; ParallelWrapper just propagates worker exceptions
via thread join, parallelism/DefaultTrainer.java:182,285). This module goes
past parity with the TPU-native equivalent of what large-scale trainers
actually need:

- ``CheckpointStore`` — crash-consistent rolling checkpoints (atomic
  rename; corrupt/truncated files detected by CRC and quarantined, never
  resumed from).
- ``CheckpointListener`` — saves through the standard listener interface
  every N iterations, so any ``fit`` loop gains recoverability without a
  special trainer.
- ``FaultTolerantTrainer`` — an epoch-aware loop that records the exact
  mid-epoch position and, on restart, fast-forwards the iterator to the
  first un-trained batch; ``run()`` = resume-if-possible-else-start.
- ``Heartbeat`` / ``FailureDetector`` — liveness files per worker process
  + a stall detector, the host-side analog of multi-slice DCN heartbeats
  (workers on other hosts cannot be observed through collectives while a
  step is wedged; a heartbeat file ages out instead).
- ``FaultInjectionListener`` — deterministic crash injection so recovery
  paths are testable (the reference has no fault-injection harness at all).

Checkpoints are the standard DL4J-style model zip (utils/model_serializer:
configuration.json + coefficients.bin + updaterState.bin + metadata), so an
elastic run's artifacts are loadable by every other tool in the framework.
"""

from __future__ import annotations

import json
import os
import tempfile
import threading
import time
import warnings
import zipfile
from typing import Callable, Optional

from deeplearning4j_tpu.optimize.listeners import TrainingListener
from deeplearning4j_tpu.utils.model_serializer import load_model, save_model

_META_NAME = "elastic.json"


class CheckpointStore:
    """Rolling crash-consistent checkpoint directory.

    Writes are atomic (tmp file in the same directory + ``os.replace``), so
    a crash mid-save can never destroy the previous good checkpoint. On
    read, every candidate is CRC-validated (``ZipFile.testzip`` over the
    DEFLATE streams) before being trusted; invalid files are renamed to
    ``*.corrupt`` and skipped.
    """

    TMP_SWEEP_AGE_S = 600  # orphan .tmp older than this is crash debris

    def __init__(self, directory: str, keep: int = 3):
        self.directory = directory
        self.keep = keep
        os.makedirs(directory, exist_ok=True)
        # sweep orphan temp files from saves killed between mkstemp and the
        # atomic rename (the exact crash window this store exists for) —
        # but only STALE ones: another live writer sharing the directory
        # finishes its save in seconds, so an age gate keeps the sweep from
        # unlinking an in-flight file under it. "now" is measured on the
        # filesystem's own clock (a fresh probe file's mtime) so it is
        # self-consistent with the candidates' mtimes even when the wall
        # clock steps between the writer and this sweep.
        fd, probe = tempfile.mkstemp(suffix=".probe", dir=directory)
        try:
            os.close(fd)
            now = os.path.getmtime(probe)
        finally:
            os.unlink(probe)
        for name in os.listdir(directory):
            if name.endswith(".tmp"):
                path = os.path.join(directory, name)
                try:
                    if now - os.path.getmtime(path) > self.TMP_SWEEP_AGE_S:
                        os.unlink(path)
                except OSError:
                    pass

    # ------------------------------------------------------------- paths
    def _path(self, iteration: int) -> str:
        return os.path.join(self.directory, f"ckpt-{iteration:010d}.zip")

    def checkpoints(self) -> list:
        """Valid checkpoint paths, oldest first."""
        out = []
        for name in sorted(os.listdir(self.directory)):
            if name.startswith("ckpt-") and name.endswith(".zip"):
                path = os.path.join(self.directory, name)
                if self._valid(path):
                    out.append(path)
        return out

    def _valid(self, path: str) -> bool:
        try:
            with zipfile.ZipFile(path) as zf:
                if zf.testzip() is not None:
                    raise zipfile.BadZipFile("CRC mismatch")
                names = zf.namelist()
                if "configuration.json" not in names or \
                        "coefficients.bin" not in names:
                    raise zipfile.BadZipFile("missing entries")
            return True
        except zipfile.BadZipFile as e:
            quarantine = path + ".corrupt"
            warnings.warn(f"quarantining corrupt checkpoint {path}: {e}")
            try:
                os.replace(path, quarantine)
            except OSError:
                pass
            return False
        except OSError as e:
            # A transient read failure (e.g. a concurrent _prune/os.replace
            # from another process sharing the directory) is NOT evidence
            # of corruption — skip the file this pass, never quarantine a
            # possibly-good newest checkpoint on it.
            warnings.warn(f"skipping unreadable checkpoint {path} "
                          f"(transient?): {e}")
            return False

    # -------------------------------------------------------------- save
    def save(self, net, extra_meta: Optional[dict] = None) -> str:
        path = self._path(net.iteration)
        fd, tmp = tempfile.mkstemp(dir=self.directory, suffix=".tmp")
        os.close(fd)
        try:
            save_model(net, tmp)
            if extra_meta:
                with zipfile.ZipFile(tmp, "a") as zf:
                    zf.writestr(_META_NAME, json.dumps(extra_meta))
            os.replace(tmp, path)
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)
        self._prune()
        return path

    def _prune(self) -> None:
        ckpts = [p for p in sorted(os.listdir(self.directory))
                 if p.startswith("ckpt-") and p.endswith(".zip")]
        for name in ckpts[:-self.keep] if self.keep > 0 else []:
            try:
                os.unlink(os.path.join(self.directory, name))
            except OSError:
                pass

    # ------------------------------------------------------------ restore
    def latest(self) -> Optional[str]:
        ckpts = self.checkpoints()
        return ckpts[-1] if ckpts else None

    def restore(self):
        """(net, extra_meta) from the newest valid checkpoint, or None.

        Falls back to the next-older checkpoint when the load itself
        fails: a process sharing the directory can prune/replace a path
        between ``checkpoints()`` validating it and the reopen here — the
        same race ``_valid`` tolerates, so a crash instead of a fallback
        would defeat that tolerance. The exclusion set is call-local: a
        filename that fails THIS restore may be validly re-saved later
        (save() reuses ``ckpt-{iteration}``), so it must not be
        blacklisted for the store's lifetime."""
        skip = set()
        while True:
            candidates = [p for p in self.checkpoints() if p not in skip]
            if not candidates:
                if skip:
                    # every candidate failed to LOAD after passing
                    # validation — that is a persistent format problem
                    # (e.g. a zip missing load_model's required entries),
                    # not the transient prune race. Returning None here
                    # would silently discard the run's entire progress by
                    # retraining from scratch.
                    raise RuntimeError(
                        "all checkpoints failed to load after validating "
                        f"({sorted(skip)}) — refusing to silently restart "
                        "from scratch; inspect or remove them to proceed")
                return None
            path = candidates[-1]
            try:
                net = load_model(path)
                meta = {}
                with zipfile.ZipFile(path) as zf:
                    if _META_NAME in zf.namelist():
                        meta = json.loads(zf.read(_META_NAME).decode())
                return net, meta
            except (OSError, zipfile.BadZipFile, KeyError) as e:
                # the reopened file can fail differently than _valid saw it:
                # truncation mid-read raises BadZipFile, a half-replaced
                # archive raises KeyError from load_model's zf.read
                warnings.warn(f"checkpoint {path} vanished/unreadable "
                              f"during restore ({e}); trying next-older")
                skip.add(path)


class CheckpointListener(TrainingListener):
    """Checkpoint every ``frequency`` iterations through the standard
    listener hook (reference analog: ModelSavingCallback,
    optimize/listeners/callbacks/ModelSavingCallback.java — which has no
    atomicity or corruption handling).

    ``health_gated`` (default True): when the model is training under an
    active health guard (optimize/health.py), a save opportunity that
    falls inside an unhealthy window — non-finite steps were skipped since
    the last save — is passed over, so the newest checkpoint stays a true
    last-known-good for the guard's rollback rung. No guard active means
    no gating."""

    def __init__(self, store: CheckpointStore, frequency: int = 100,
                 meta_fn: Optional[Callable[[], dict]] = None,
                 health_gated: bool = True):
        self.store = store
        self.frequency = frequency
        self.meta_fn = meta_fn
        self.health_gated = health_gated
        self.saved = 0
        self.skipped_unhealthy = 0

    def iteration_done(self, model, iteration: int):
        if iteration % self.frequency != 0:
            return
        if self.health_gated:
            health = getattr(model, "_health", None)
            if health is not None and not health.healthy_to_save():
                self.skipped_unhealthy += 1
                return
        self.store.save(model, self.meta_fn() if self.meta_fn else None)
        self.saved += 1


class FaultTolerantTrainer:
    """Elastic training loop: checkpoint every N iterations, resume from
    the last good checkpoint after any crash — process kill included —
    without retraining completed batches.

    ``iterator_factory`` must return a fresh (or reset-able) iterator for
    an epoch each time it is called; determinism of the stream order is the
    caller's contract (the same requirement Spark's Export training mode
    places on its saved minibatch files).
    """

    def __init__(self, net, store: CheckpointStore, frequency: int = 50):
        self.net = net
        self.store = store
        self.frequency = frequency
        self._batch_in_epoch = 0
        # net.iteration as of entering the in-flight batch, None between
        # batches — lets the emergency save tell a crash that landed AFTER
        # the update was applied (a listener raising post-step) from one
        # before it, so resume neither retrains nor drops that batch
        self._iter_at_batch_start: Optional[int] = None

    # ------------------------------------------------------------- meta
    def _meta(self) -> dict:
        return {"epoch": self.net.epoch,
                "batch_in_epoch": self._batch_in_epoch}

    # -------------------------------------------------------------- fit
    def fit(self, iterator_factory: Callable[[], object], epochs: int,
            start_epoch: int = 0, skip_batches: int = 0):
        try:
            return self._fit_loop(iterator_factory, epochs, start_epoch,
                                  skip_batches)
        except BaseException as exc:
            # best-effort emergency checkpoint at the crash point, so a
            # restart resumes from HERE instead of the last periodic save
            self._emergency_save(exc)
            raise

    def _fit_loop(self, iterator_factory, epochs, start_epoch, skip_batches):
        net = self.net
        for epoch in range(start_epoch, epochs):
            net.epoch = epoch
            for listener in net.listeners:
                listener.on_epoch_start(net)
            it = iterator_factory()
            if hasattr(it, "reset"):
                it.reset()
            self._batch_in_epoch = 0
            for ds in it:
                if skip_batches > 0:
                    skip_batches -= 1
                    self._batch_in_epoch += 1
                    continue
                self._iter_at_batch_start = net.iteration
                net._fit_batch(ds)
                self._iter_at_batch_start = None
                self._batch_in_epoch += 1
                if net.iteration % self.frequency == 0:
                    self.store.save(net, self._meta())
            if skip_batches > 0:
                # the resumed stream produced fewer batches this epoch than
                # when the checkpoint was written — the iterator_factory
                # determinism contract is violated; without this warning the
                # leftover skips silently swallow head batches of the NEXT
                # epoch
                warnings.warn(
                    f"resume skip position exceeded epoch {epoch} length by "
                    f"{skip_batches} batches — the iterator_factory is not "
                    "producing the same stream it did when the checkpoint "
                    "was written; dropping the leftover skips")
                skip_batches = 0
            for listener in net.listeners:
                listener.on_epoch_end(net)
        net.epoch = epochs
        self.store.save(net, {"epoch": epochs, "batch_in_epoch": 0,
                              "complete": True})
        return net

    def _emergency_save(self, exc) -> None:
        """Crash checkpoint, guarded so a second failure (disk full, net in
        a broken state) cannot mask the original exception."""
        try:
            net = self.net
            batch = self._batch_in_epoch
            if (self._iter_at_batch_start is not None
                    and net.iteration > self._iter_at_batch_start):
                # the update(s) for the in-flight batch were applied before
                # the raise (e.g. a listener crashed post-step) but the
                # position counter had not advanced yet — count the batch as
                # trained so resume does not apply it twice
                batch += 1
            self.store.save(net, {"epoch": net.epoch,
                                  "batch_in_epoch": batch,
                                  "emergency": True,
                                  "error": repr(exc)})
        except BaseException as save_exc:  # noqa: BLE001 — must not mask exc
            try:
                warnings.warn(
                    f"emergency checkpoint failed ({save_exc!r}); resuming "
                    "will fall back to the last periodic checkpoint")
            except BaseException:
                pass

    # -------------------------------------------------------------- run
    def run(self, iterator_factory: Callable[[], object], epochs: int):
        """Resume from the newest checkpoint if one exists, else start
        fresh. Returns the trained network (which replaces ``self.net`` on
        resume).

        Checkpoints written by this trainer carry the exact (epoch,
        batch_in_epoch) position. A checkpoint without it (e.g. written by
        a bare CheckpointListener with no ``meta_fn``) would otherwise
        silently re-train every completed batch on top of the restored
        weights; instead the position is derived from the restored
        iteration counter and the stream length (one counting pass over a
        fresh iterator — cheap, and the factory contract already promises
        a repeatable stream)."""
        restored = self.store.restore()
        if restored is None:
            return self.fit(iterator_factory, epochs)
        net, meta = restored
        if meta.get("complete"):
            self.net = net
            return net
        if "epoch" in meta and "batch_in_epoch" in meta:
            start_epoch = meta["epoch"]
            skip = meta["batch_in_epoch"]
        else:
            per_epoch = sum(1 for _ in iterator_factory())
            if per_epoch == 0:
                raise ValueError("iterator_factory produced an empty stream")
            start_epoch = net.iteration // per_epoch
            skip = net.iteration % per_epoch
            warnings.warn(
                "checkpoint has no elastic position metadata; derived "
                f"resume point epoch={start_epoch} batch={skip} from "
                f"iteration={net.iteration} and stream length {per_epoch}")
        net.listeners = self.net.listeners
        self.net = net
        return self.fit(iterator_factory, epochs,
                        start_epoch=start_epoch, skip_batches=skip)


class Heartbeat:
    """Periodic liveness file for one worker process.

    A daemon thread rewrites ``{pid, ts}`` every ``interval`` seconds;
    observers call ``FailureDetector.dead_workers`` to find workers whose
    file has aged past the timeout. This is the host-side stand-in for
    multi-slice DCN liveness: a worker wedged inside a device step stops
    heartbeating even though its process is alive."""

    #: consecutive beat() failures before the loop surfaces a warning
    WARN_AFTER_FAILURES = 5

    def __init__(self, path: str, interval: float = 1.0):
        self.path = path
        self.interval = interval
        self.consecutive_failures = 0
        self._warned = False
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def beat(self) -> None:
        tmp = self.path + ".tmp"
        with open(tmp, "w") as fh:
            json.dump({"pid": os.getpid(), "ts": time.time()}, fh)
        os.replace(tmp, self.path)

    def start(self) -> "Heartbeat":
        self.beat()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self

    def _loop(self) -> None:
        # a transient OSError from beat() (disk-full, NFS blip) must NOT
        # kill the loop — a dead heartbeat thread reads as a dead WORKER to
        # every observer. Keep beating; the next success clears the streak.
        while not self._stop.wait(self.interval):
            try:
                self.beat()
                self.consecutive_failures = 0
                self._warned = False
            except OSError as e:
                self.consecutive_failures += 1
                if (self.consecutive_failures >= self.WARN_AFTER_FAILURES
                        and not self._warned):
                    self._warned = True
                    warnings.warn(
                        f"heartbeat {self.path} failed "
                        f"{self.consecutive_failures} consecutive times "
                        f"({e!r}); still retrying")

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)

    def __enter__(self) -> "Heartbeat":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


class FailureDetector:
    """Scan a directory of heartbeat files for stalled/dead workers.

    Staleness is CHANGE-detected on the observer's monotonic clock: the
    persisted wall-clock ``ts`` acts as a version number, and a worker is
    dead once its ``ts`` has not advanced for ``timeout`` seconds of
    *observer* time. Comparing the writer's wall clock against the
    observer's (the old scheme) declares every worker dead the moment
    either clock steps under NTP/VM migration."""

    def __init__(self, directory: str, timeout: float = 10.0):
        self.directory = directory
        self.timeout = timeout
        # worker -> (last persisted ts seen, observer-monotonic instant
        # at which that value was first observed)
        self._observed: dict = {}

    def workers(self) -> dict:
        out = {}
        if not os.path.isdir(self.directory):
            return out
        for name in os.listdir(self.directory):
            if not name.endswith(".heartbeat"):
                continue
            try:
                with open(os.path.join(self.directory, name)) as fh:
                    out[name[:-len(".heartbeat")]] = json.load(fh)
            except (OSError, json.JSONDecodeError):
                out[name[:-len(".heartbeat")]] = None
        return out

    def dead_workers(self, now: Optional[float] = None,
                     timeout: Optional[float] = None) -> list:
        """Workers whose heartbeat has not advanced for ``timeout``
        observer-seconds (or whose file is unreadable). ``now`` overrides
        the observer's ``time.monotonic()`` reading — test hook.
        ``timeout`` overrides the constructor's for this call only, so
        one detector can answer both a short *suspect* question and a
        long *dead* question off the same observation table (the fleet
        federation marks a host suspect on missed beats well before the
        dead verdict — or any TCP error — lands)."""
        mono = time.monotonic() if now is None else now
        stale_after = self.timeout if timeout is None else timeout
        seen = self.workers()
        # forget workers whose heartbeat file vanished, so a re-created
        # one starts a fresh staleness window
        self._observed = {w: v for w, v in self._observed.items()
                          if w in seen}
        dead = []
        for worker, info in seen.items():
            if info is None:
                dead.append(worker)
                continue
            ts = info.get("ts", 0)
            prev = self._observed.get(worker)
            if prev is None or prev[0] != ts:
                # first observation, or the persisted ts advanced since
                # the last scan: liveness proven on the observer's clock
                self._observed[worker] = (ts, mono)
            elif mono - prev[1] > stale_after:
                dead.append(worker)
        return sorted(dead)


class FaultInjectionListener(TrainingListener):
    """Raise at a chosen iteration — deterministic crash injection for
    recovery tests (the reference has no fault-injection harness)."""

    class InjectedFault(RuntimeError):
        pass

    def __init__(self, at_iteration: int):
        self.at_iteration = at_iteration

    def iteration_done(self, model, iteration: int):
        if iteration == self.at_iteration:
            raise self.InjectedFault(f"injected fault at {iteration}")
