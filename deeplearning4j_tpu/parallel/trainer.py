"""ParallelWrapper: data-parallel training as one sharded XLA program.

Reference semantics reproduced (parallelism/ParallelWrapper.java:53):

- ``AVERAGING`` mode (:148-305): each worker takes ``averaging_frequency`` local
  SGD steps on its own replica, then parameters — and optionally updater state
  (:273-305 averageUpdatersState) — are averaged across workers
  (Nd4j.averageAndPropagate :261). Here: `lax.scan` of local steps inside
  `shard_map`, then `lax.pmean` on params/updater-state over the ``data`` axis.
- ``SHARED_GRADIENTS`` mode (:54-69, SymmetricTrainer.java:23-88 +
  EncodingHandler threshold broadcast): gradients are shared every step. Here:
  `lax.pmean` on gradients inside the step — the idiomatic TPU path (replicas
  never diverge, no separate broadcast needed; ICI carries the reduction).

Unlike the reference there are no worker threads, no replica re-sync, and no
blocking queues: the whole averaging round (W workers x F local steps) is ONE
jitted program; XLA overlaps the per-device compute and the ICI collectives.

Equivalence contract (ported from
TestCompareParameterAveragingSparkVsSingleMachine.java): with
averaging_frequency=1 and SGD, training on N devices with per-device batch B
equals single-device training on the concatenated N*B batch, to float tolerance.
"""

from __future__ import annotations

import time
import warnings
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from deeplearning4j_tpu.optimize.fused_fit import (build_step_core,
                                                   make_scan_body)
from deeplearning4j_tpu.optimize.listeners import TrainingListener

# jax >= 0.6 exposes shard_map at top level with check_vma; older releases
# keep it in jax.experimental with the check_rep spelling
try:
    _shard_map = jax.shard_map
    _SHARD_MAP_CHECK_KW = "check_vma"
except AttributeError:
    from jax.experimental.shard_map import shard_map as _shard_map
    _SHARD_MAP_CHECK_KW = "check_rep"
from deeplearning4j_tpu.parallel.mesh import DATA_AXIS, data_mesh

AVERAGING = "averaging"
SHARED_GRADIENTS = "shared_gradients"


class ParallelWrapper:
    """Data-parallel trainer wrapping any net exposing the functional contract
    ``_loss(params, state, x, y, input_mask, label_mask, *, train, rng)`` plus
    ``params / state / updater_state / conf.updater`` (MultiLayerNetwork and
    ComputationGraph both qualify).
    """

    def __init__(self, net, workers: Optional[int] = None,
                 averaging_frequency: int = 1, mode: str = AVERAGING,
                 average_updaters: bool = True, mesh: Optional[Mesh] = None,
                 report_score: bool = True, health_guard=True):
        if mode not in (AVERAGING, SHARED_GRADIENTS):
            raise ValueError(f"Unknown mode '{mode}'")
        if averaging_frequency < 1:
            raise ValueError("averaging_frequency must be >= 1")
        self.net = net
        self.mesh = mesh if mesh is not None else data_mesh(workers)
        self.workers = self.mesh.devices.size
        self.averaging_frequency = averaging_frequency
        self.mode = mode
        self.average_updaters = average_updaters
        self.report_score = report_score
        # numerical-health guard (optimize/health.py): the guarded step core
        # skips non-finite worker steps on device and the policy handles
        # divergence host-side. True -> default policy per fit() call,
        # None/False -> off, or pass a configured HealthPolicy.
        self.health_guard = health_guard
        self._policy = None  # active policy, set for the duration of fit()
        # mid-stream batches whose size didn't match the stream's (dropped
        # with a warning — see fit); genuine trailing partials not counted
        self.dropped_batches = 0
        # last round's phase wall times (SparkTrainingStats analog)
        self.last_phase_timings: dict = {}
        self._round_cache: dict = {}

    # ------------------------------------------------------------------ build
    def _build_round(self, has_im: bool, has_lm: bool, guarded: bool):
        net = self.net
        pmean_grads = self.mode == SHARED_GRADIENTS
        avg_params = self.mode == AVERAGING
        average_updaters = self.average_updaters
        # the shared step core (forward, reg grads, normalization, updater,
        # center-loss update) — identical to the single-device fit paths; the
        # pmean hook runs between regularization and normalization, so
        # SHARED_GRADIENTS normalizes the GLOBAL gradient exactly as a single
        # device would on the concatenated batch (the module's parity
        # contract) while AVERAGING normalizes each worker's local step.
        # Under the guard the same ordering means a SHARED_GRADIENTS pmean
        # poisons every replica identically, so all replicas skip the same
        # step and stay in lockstep.
        core = build_step_core(
            net,
            grad_transform=((lambda g: lax.pmean(g, DATA_AXIS))
                            if pmean_grads else None),
            guarded=guarded)

        def device_round(params, opt, state, rng, it0, xs, ys, ims, lms):
            """Runs on ONE device's shard: F local steps, then averaging.

            xs/ys/ims/lms: [F, B_local, ...] stacks of this device's minibatches.
            """
            didx = lax.axis_index(DATA_AXIS)

            def sharded_core(params, opt_state, st, step_rng, it, x, y, im,
                             lm, carry):
                # the host stacks zero-filled placeholder masks for unmasked
                # streams (one scan signature); drop them before the loss
                return core(params, opt_state, st, step_rng, it, x, y,
                            im if has_im else None,
                            lm if has_lm else None, carry)

            body = make_scan_body(
                sharded_core,
                rng_fn=lambda it: jax.random.fold_in(
                    jax.random.fold_in(rng, it.astype(jnp.int32)), didx),
                guarded=guarded)
            (params, opt, state, _), scanned = lax.scan(
                body, (params, opt, state, it0), (xs, ys, ims, lms))
            if guarded:
                losses, skip_flags = scanned
            else:
                losses = scanned
            if avg_params:
                params = lax.pmean(params, DATA_AXIS)
                if average_updaters:
                    opt = lax.pmean(opt, DATA_AXIS)
            # persistent layer state (e.g. BN running stats) is averaged like the
            # reference's full-model averaging
            state = lax.pmean(state, DATA_AXIS)
            if guarded:
                # per-step stats kept: [F] mean losses + [F] skip fractions
                # (fraction of workers that skipped that local step) — one
                # pair of small fetches per round for the health policy
                losses = lax.pmean(losses, DATA_AXIS)
                skips = lax.pmean(skip_flags, DATA_AXIS)
                return params, opt, state, losses, skips
            loss = lax.pmean(jnp.mean(losses), DATA_AXIS)
            return params, opt, state, loss

        batch_spec = P(None, DATA_AXIS)
        n_out = 5 if guarded else 4
        fn = _shard_map(
            device_round, mesh=self.mesh,
            in_specs=(P(), P(), P(), P(), P(),
                      batch_spec, batch_spec, batch_spec, batch_spec),
            out_specs=(P(),) * n_out,
            **{_SHARD_MAP_CHECK_KW: False})
        # params/opt/state are rebound from the round's outputs
        return jax.jit(fn, donate_argnums=(0, 1, 2))

    def _get_round(self, key):
        if key not in self._round_cache:
            self._round_cache[key] = self._build_round(key[-3], key[-2],
                                                       key[-1])
        return self._round_cache[key]

    def _invalidate_programs(self):
        """Health-policy hook: the base LR is baked into the compiled round
        (and step) programs, so an LR backoff must drop them."""
        self._round_cache.clear()

    # -------------------------------------------------------------------- fit
    def fit(self, iterator, epochs: int = 1):
        """Feed W*F minibatches per averaging round (reference: ParallelWrapper
        .fit :409-487 — each worker consumes its own minibatches; incomplete
        final rounds are dropped, matching the reference's skip of trailing
        partial worker groups)."""
        from deeplearning4j_tpu.optimize.health import resolve_health_policy

        net = self.net
        W, F = self.workers, self.averaging_frequency
        need = W * F
        expected_batch = None
        policy = resolve_health_policy(self.health_guard)
        prev_health = getattr(net, "_health", None)
        self._policy = policy
        if policy is not None:
            policy.bind(net, invalidate=self._invalidate_programs)
            # expose on the net too, so health-gated checkpoint listeners
            # (elastic.CheckpointListener) see the active policy
            net._health = policy
        try:
            for _ in range(epochs):
                for listener in getattr(net, "listeners", []):
                    listener.on_epoch_start(net)
                if hasattr(iterator, "reset"):
                    iterator.reset()
                buf = []
                stream = iter(iterator)
                ds = next(stream, None)
                while ds is not None:
                    nxt = next(stream, None)
                    b = np.asarray(ds.features).shape[0]
                    if expected_batch is None:
                        expected_batch = b
                    if b != expected_batch:
                        # a genuinely-final undersized minibatch is a trailing
                        # partial: skipped silently like trailing partial
                        # worker groups (static shapes keep one XLA program).
                        # Any OTHER mismatch is data the caller expects to
                        # train on — count it and warn instead of silently
                        # losing it.
                        if not (nxt is None and b < expected_batch):
                            self.dropped_batches += 1
                            warnings.warn(
                                f"ParallelWrapper dropped a mid-stream "
                                f"minibatch of size {b} (expected "
                                f"{expected_batch}): all non-trailing "
                                f"minibatches must share one batch size "
                                f"({self.dropped_batches} dropped so far)",
                                stacklevel=2)
                        ds = nxt
                        continue
                    buf.append(ds)
                    if len(buf) == need:
                        self._fit_round(buf)
                        buf = []
                    ds = nxt
                # trailing partial group: dropped (reference parity)
                for listener in getattr(net, "listeners", []):
                    listener.on_epoch_end(net)
                if hasattr(net, "epoch"):
                    net.epoch += 1
            return self.net
        finally:
            self._policy = None
            if policy is not None:
                net._health = prev_health

    def _fit_round(self, batches):
        """One averaging round from W*F host minibatches."""
        net = self.net
        W, F = self.workers, self.averaging_frequency
        t_prep0 = time.perf_counter()
        feats = np.stack([np.asarray(b.features) for b in batches])  # [W*F, B, ...]
        labs = np.stack([np.asarray(b.labels) for b in batches])
        has_im = any(b.features_mask is not None for b in batches)
        has_lm = any(b.labels_mask is not None for b in batches)
        if has_im and not all(b.features_mask is not None for b in batches):
            raise ValueError("Mixed masked/unmasked feature batches in one "
                             "averaging round are not supported")
        if has_lm and not all(b.labels_mask is not None for b in batches):
            raise ValueError("Mixed masked/unmasked label batches in one "
                             "averaging round are not supported")
        ims = (np.stack([np.asarray(b.features_mask) for b in batches])
               if has_im else np.zeros(feats.shape[:2], np.float32))
        lms = (np.stack([np.asarray(b.labels_mask) for b in batches])
               if has_lm else np.zeros(feats.shape[:2], np.float32))

        # [W*F, B, ...] -> [F, W*B, ...]: round-robin assignment of minibatches
        # to workers (batch i goes to worker i % W, matching the reference's
        # round-robin feeding), so along the sharded axis each worker's F
        # batches are contiguous per step.
        def regroup(a):
            # [W*F, B, ...] -> [F, W, B, ...] -> [F, W*B, ...]
            fwb = a.reshape(F, W, *a.shape[1:])
            return fwb.reshape(F, W * a.shape[1], *a.shape[2:])

        feats, labs, ims, lms = map(regroup, (feats, labs, ims, lms))
        guarded = self._policy is not None
        key = (feats.shape, labs.shape, has_im, has_lm, guarded)
        rnd = self._get_round(key)
        t_dev0 = time.perf_counter()
        base = (net._rng_base() if hasattr(net, "_rng_base")
                else jax.random.PRNGKey(net.conf.seed))
        rng = jax.random.fold_in(base, net.iteration)
        out = rnd(
            net.params, net.updater_state, net.state, rng,
            jnp.asarray(net.iteration, jnp.float32), feats, labs, ims, lms)
        scores_h = skips_h = None
        if guarded:
            params, opt, state, losses, skips = out
        else:
            params, opt, state, loss = out
        net.params, net.updater_state, net.state = params, opt, state
        it0 = net.iteration
        net.iteration += F
        listeners = getattr(net, "listeners", [])
        # timings need a device sync; report_score already pays one — as
        # does the guarded round's stats fetch. report_score=False exists
        # precisely to let the next round's host prep overlap the device
        # compute — only the guard or a listener that actually consumes
        # phase timings may re-introduce the block.
        want_timings = self.report_score or any(
            type(ls).on_phase_timings is not TrainingListener.on_phase_timings
            for ls in listeners)
        if guarded:
            # ONE small host fetch per round: [F] mean losses + [F] skip
            # fractions together
            scores_h, skips_h = map(np.asarray,
                                    jax.device_get((losses, skips)))
            if self.report_score:
                # mean over the round's F per-step pmean'd losses — equal to
                # the unguarded round's pmean(mean(losses)) scalar
                net.score_value = float(np.mean(scores_h))
        elif self.report_score:
            net.score_value = float(loss)  # forces device round completion
        elif want_timings:
            jax.block_until_ready(loss)
        if want_timings:
            t_end = time.perf_counter()
            # per-round phase stats (reference: SparkTrainingStats —
            # data-fetch / fit / aggregation per worker round). Averaging
            # is INSIDE the jitted device round here (one pmean), so it
            # cannot be timed separately from fit — reported as part of
            # device_round_ms, with the key present so consumers see the
            # design, not a hole.
            self.last_phase_timings = {
                "host_prep_ms": (t_dev0 - t_prep0) * 1e3,
                "device_round_ms": (t_end - t_dev0) * 1e3,
                "averaging": "in-device-round",
                "round_iterations": F,
                "workers": W,
            }
            for listener in listeners:
                listener.on_phase_timings(net, dict(self.last_phase_timings))
        it_done = net.iteration
        if guarded:
            # may back off the LR (dropping cached rounds), roll back, or
            # raise — BEFORE the listener round, so gated checkpoint
            # listeners see this round's skip state
            self._policy.observe(net, scores_h, skips_h, it0)
        for listener in listeners:
            listener.iteration_done(net, it_done)

    # ------------------------------------------------------------- utilities
    def average_models(self):
        """No-op: params live once, replicated by XLA (reference needed explicit
        averageModelsParams across replicas; here averaging happens inside the
        jitted round)."""
        return self.net
