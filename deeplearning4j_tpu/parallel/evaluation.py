"""Distributed (map-reduce) evaluation.

Reference: SparkDl4jMultiLayer.evaluate (impl/multilayer/SparkDl4jMultiLayer
.java:443-540) — executors each evaluate their partitions into an IEvaluation,
then the results are reduced with IEvaluation.merge. Here the forward pass is
sharded over the mesh (the "executors") and — by default — the reduce happens
ON DEVICE: each mesh shard accumulates its confusion/top-N/loss counts inside
the fused evaluation program and XLA's cross-replica sum IS IEvaluation.merge.
Only the final [C, C] count matrix crosses to host, once per evaluation run,
instead of per-batch logit transfers. ``fused=False`` keeps the original
per-batch map-reduce (sharded forward, host-side eval + merge per batch).
"""

from __future__ import annotations

import copy
from typing import Optional

import numpy as np
from jax.sharding import Mesh

from deeplearning4j_tpu.parallel.inference import ParallelInference
from deeplearning4j_tpu.parallel.mesh import data_mesh


def evaluate_on_mesh(net, iterator, mesh: Optional[Mesh] = None,
                     evaluation=None, *, fused: Optional[bool] = None,
                     eval_batches: Optional[int] = None):
    """Evaluate ``net`` over all batches of ``iterator`` with mesh-sharded
    forwards. ``evaluation`` is a prototype instance (configuration like
    label names / top_n is preserved in the result). Default path: the
    device-side fused evaluator with the batch axis sharded over ``mesh``
    (merge = on-device sum, one host fetch). ``fused=False``: per-batch
    forward + host-side eval/merge (the original map-reduce)."""
    from deeplearning4j_tpu.evaluation.classification import Evaluation

    if evaluation is None:
        evaluation = Evaluation()
    if hasattr(iterator, "reset"):
        iterator.reset()

    if fused is None or fused:
        from deeplearning4j_tpu.evaluation.fused_eval import FusedEvalDriver
        driver = FusedEvalDriver(net, eval_batches=eval_batches,
                                 mesh=mesh if mesh is not None else data_mesh())
        return driver.evaluate(iterator, copy.deepcopy(evaluation))

    inf = ParallelInference(net, mesh=mesh)
    result = None
    for ds in iterator:
        out = inf.output(ds.features, mask=ds.features_mask)
        partial = copy.deepcopy(evaluation)
        partial.eval(np.asarray(ds.labels), out,
                     mask=None if ds.labels_mask is None
                     else np.asarray(ds.labels_mask))
        if result is None:
            result = partial
        else:
            result.merge(partial)
    return result if result is not None else evaluation
