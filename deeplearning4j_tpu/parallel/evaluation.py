"""Distributed (map-reduce) evaluation.

Reference: SparkDl4jMultiLayer.evaluate (impl/multilayer/SparkDl4jMultiLayer
.java:443-540) — executors each evaluate their partitions into an IEvaluation,
then the results are reduced with IEvaluation.merge. Here the forward pass is
sharded over the mesh (the "executors"), each batch becomes a partial
evaluation on host, and the reduce is IEvaluation.merge — same algebra, ICI-fed.
"""

from __future__ import annotations

import copy
from typing import Optional

import numpy as np
from jax.sharding import Mesh

from deeplearning4j_tpu.parallel.inference import ParallelInference


def evaluate_on_mesh(net, iterator, mesh: Optional[Mesh] = None,
                     evaluation=None):
    """Evaluate ``net`` over all batches of ``iterator`` with mesh-sharded
    forwards; one partial evaluation per batch ("partition"), merged at the
    end. ``evaluation`` is a prototype instance (deep-copied per partial, so
    constructor configuration like label names is preserved)."""
    from deeplearning4j_tpu.evaluation.classification import Evaluation

    if evaluation is None:
        evaluation = Evaluation()
    inf = ParallelInference(net, mesh=mesh)
    result = None
    if hasattr(iterator, "reset"):
        iterator.reset()
    for ds in iterator:
        out = inf.output(ds.features, mask=ds.features_mask)
        partial = copy.deepcopy(evaluation)
        partial.eval(np.asarray(ds.labels), out,
                     mask=None if ds.labels_mask is None
                     else np.asarray(ds.labels_mask))
        if result is None:
            result = partial
        else:
            result.merge(partial)
    return result if result is not None else evaluation
