"""Unified supervised serving runtime: one lifecycle for every loop thread.

Before this module, three hand-rolled thread stacks (the
``ParallelInference`` coalescer/completer pair, the ``GenerationServer``
decode loop, and the ``StreamingBroker`` publisher threads) each
reimplemented queues, sentinels, drain/close choreography, and crash
recovery. ``ServingLoop`` defines those semantics exactly once:

    NEW --start()--> RUNNING --begin_drain()--> DRAINING --close()--> CLOSED
                        |                           |
                        +----------- close() -------+--------------> CLOSED

* ``start()`` is legal only from NEW (``IllegalLoopTransition`` otherwise).
* ``begin_drain()`` is idempotent: a no-op from DRAINING or CLOSED.
* ``close()`` is idempotent and re-entrant from any thread: the first
  caller performs the shutdown, concurrent callers block on the same
  completion event.
* ``restart()`` is legal only from CLOSED and is how the supervisor
  implements supervised restart.

Two hosting modes:

* **inbox mode** (``handler=...``): the loop owns a bounded
  ``queue.Queue`` inbox and a pool of worker threads consuming from it.
  One sentinel discipline: ``close()`` puts exactly one ``_SENTINEL``;
  each worker that sees it decrements the live count and re-puts it for
  the next worker, so a single token walks the whole pool down.
* **tick mode** (``tick=...``): the loop owns one thread repeatedly
  calling ``tick()`` until it returns False or the loop leaves RUNNING /
  DRAINING. ``wake`` is called (outside any runtime lock) whenever the
  state machine advances, so a tick body blocked on its own condition
  variable can re-check state promptly.

``LoopSupervisor`` watches registered loops, detects loop-thread death
(a crash recorded by the loop, or the liveness backstop: a RUNNING loop
whose threads are all gone without a clean exit), and runs the uniform
recovery contract: finish the crash (fail leftovers, release waiters),
call the owner's ``on_death`` hook (where servers fail their in-flight
futures with the typed ``LoopCrashed``), and optionally restart the loop
with exponential backoff.

Lock ranks (see ``analysis/instrument.py``): ``ServingLoop._cond`` is
rank 25, ``LoopSupervisor._lock`` rank 55. The runtime NEVER invokes
user callbacks (``handler``, ``wake``, ``on_leftover``,
``on_worker_exit``, ``on_death``) while holding ``_cond``, and the
supervisor never calls loop methods while holding ``_lock``.
"""

from __future__ import annotations

import enum
import queue
import threading
import time
from typing import Any, Callable, List, Optional


class LoopError(RuntimeError):
    """Base class for serving-runtime lifecycle errors."""


class IllegalLoopTransition(LoopError):
    """A lifecycle method was called from a state that forbids it."""


class LoopClosed(LoopError):
    """``put()`` (or a handler's downstream put) hit a CLOSED loop."""


class LoopCrashed(LoopError):
    """The owning loop thread died; in-flight work was failed with this."""


class LoopKilled(BaseException):
    """Chaos-injected loop-thread death.

    Deliberately NOT an ``Exception``: server loop bodies catch
    ``Exception`` to fail in-flight work and keep serving, and the whole
    point of ``kill_during_drain`` chaos is to escape those handlers and
    take the thread down, exactly like an untrappable runtime failure.
    Futures are never failed with this directly — the recovery path
    wraps it in ``LoopCrashed`` (a plain ``Exception``).
    """


class LoopState(enum.Enum):
    NEW = "new"
    RUNNING = "running"
    DRAINING = "draining"
    CLOSED = "closed"


NEW = LoopState.NEW
RUNNING = LoopState.RUNNING
DRAINING = LoopState.DRAINING
CLOSED = LoopState.CLOSED


class _Token:
    """Control token circulated through an inbox (never user data)."""

    __slots__ = ("name",)

    def __init__(self, name: str):
        self.name = name

    def __repr__(self):  # pragma: no cover - debugging aid
        return f"<loop-token {self.name}>"


_SENTINEL = _Token("sentinel")   # one per close(); walks the worker pool
_RESIGN = _Token("resign")       # retires exactly one worker
EXIT = _Token("exit")            # handler return value: retire this worker


class ServingLoop:
    """One supervised loop: owned thread(s), bounded inbox, one sentinel
    discipline, and the NEW → RUNNING → DRAINING → CLOSED state machine.

    Exactly one of ``handler`` (inbox mode) or ``tick`` (tick mode) must
    be given. In inbox mode ``handler(item)`` may return:

    * ``None`` — item consumed, get the next one;
    * ``EXIT`` — retire this worker (its slot is gone until
      ``set_workers``/``restart`` respawns it);
    * any other value — a *carried* item handed back as the next input
      (head-of-line carry for batch-boundary flushes).

    In tick mode ``tick()`` returns True to keep running, False to stop
    cleanly; ``wake()`` is invoked when the state machine advances.
    """

    # Runtime-owned state: written only under ``_cond`` by lifecycle
    # methods, read lock-free on loop threads' hot paths (declared for
    # the conc-loop-ownership analyzer rule).
    _LOOP_OWNED = ("_state", "_closed_evt", "_inbox", "_supervisor")
    _LOOP_LOCK = "_cond"

    def __init__(self, name: str, *,
                 handler: Optional[Callable[[Any], Any]] = None,
                 tick: Optional[Callable[[], bool]] = None,
                 wake: Optional[Callable[[], None]] = None,
                 workers: int = 1,
                 max_workers: Optional[int] = None,
                 inbox: Optional[queue.Queue] = None,
                 inbox_maxsize: int = 0,
                 on_leftover: Optional[Callable[[Any], None]] = None,
                 on_worker_exit: Optional[
                     Callable[["ServingLoop", Optional[BaseException]],
                              None]] = None,
                 chaos: Any = None,
                 daemon: bool = True):
        if (handler is None) == (tick is None):
            raise ValueError("exactly one of handler= or tick= is required")
        self.name = name
        self._handler = handler
        self._tick = tick
        self._wake = wake
        self._daemon = daemon
        self._cond = threading.Condition()
        self._state = LoopState.NEW
        self._workers = max(1, int(workers))
        self._max_workers = max(self._workers,
                                int(max_workers or self._workers))
        self._inbox_maxsize = int(inbox_maxsize)
        self._external_inbox = inbox is not None
        self._inbox: Optional[queue.Queue] = None
        if handler is not None:
            self._inbox = inbox if inbox is not None \
                else queue.Queue(maxsize=self._inbox_maxsize)
        self._on_leftover = on_leftover
        self._on_worker_exit = on_worker_exit
        self._chaos = chaos
        self._threads: List[threading.Thread] = []
        self._live = 0              # workers not yet exited (under _cond)
        self._seq = 0               # worker name sequence
        self._clean_exit = False    # tick loop returned False (under _cond)
        self._crash_exc: Optional[BaseException] = None
        self._crash_handled = False
        self._closer: Optional[int] = None   # thread ident of sole closer
        self._retired = False    # deliberate close(): restart() forbidden
        self._closed_evt = threading.Event()
        self._supervisor: Optional["LoopSupervisor"] = None
        self.generation = 0
        self.restarts = 0

    # ------------------------------------------------------------- state
    @property
    def state(self) -> LoopState:
        return self._state

    @property
    def crashed(self) -> Optional[BaseException]:
        """First exception that took a loop thread down, else None."""
        with self._cond:
            return self._crash_exc

    @property
    def alive_workers(self) -> int:
        with self._cond:
            return self._live

    @property
    def threads(self) -> List[threading.Thread]:
        with self._cond:
            return list(self._threads)

    def stats(self) -> dict:
        with self._cond:
            return {
                "name": self.name,
                "state": self._state.value,
                "workers": self._live,
                "generation": self.generation,
                "restarts": self.restarts,
                "crashed": self._crash_exc is not None,
            }

    # --------------------------------------------------------- lifecycle
    def start(self) -> "ServingLoop":
        with self._cond:
            if self._state is not LoopState.NEW:
                raise IllegalLoopTransition(
                    f"{self.name}: start() from {self._state.value}")
            self._state = LoopState.RUNNING
            self._spawn_locked()
        return self

    def _spawn_locked(self) -> None:
        """Spawn the owned thread(s). Caller holds ``_cond``."""
        self._clean_exit = False
        if self._tick is not None:
            t = threading.Thread(target=self._tick_main, daemon=self._daemon,
                                 name=self.name)
            self._threads.append(t)
            self._live += 1
            t.start()
            return
        for _ in range(self._workers):
            self._spawn_worker_locked()

    def _spawn_worker_locked(self) -> None:
        self._seq += 1
        suffix = "" if self._max_workers == 1 else f"-{self._seq}"
        t = threading.Thread(target=self._worker_main, daemon=self._daemon,
                             name=f"{self.name}{suffix}")
        self._threads.append(t)
        self._live += 1
        t.start()

    def begin_drain(self) -> None:
        """RUNNING → DRAINING. Idempotent: no-op from DRAINING/CLOSED."""
        with self._cond:
            if self._state is not LoopState.RUNNING:
                return
            self._state = LoopState.DRAINING
            self._cond.notify_all()
        if self._wake is not None:
            self._wake()

    def close(self, timeout: float = 30.0) -> None:
        """DRAINING/RUNNING/NEW → CLOSED. Idempotent and re-entrant: the
        first caller shuts the loop down, concurrent callers wait on the
        same completion event."""
        with self._cond:
            # a deliberate close is final even when it loses the race to
            # a crash: a pending supervised restart must not resurrect a
            # loop the owner just closed
            self._retired = True
            if self._state is LoopState.CLOSED or self._closer is not None:
                sole = False
            else:
                sole = True
                self._closer = threading.get_ident()
                self._state = LoopState.CLOSED
                self._cond.notify_all()
                live = self._live
                threads = list(self._threads)
        if not sole:
            self._closed_evt.wait(timeout)
            sup = self._supervisor
            if sup is not None:
                sup.unwatch(self)
            return
        if self._wake is not None:
            self._wake()
        deadline = time.monotonic() + max(0.0, timeout)
        if self._inbox is not None and live > 0:
            # ONE sentinel walks the whole pool down (each worker re-puts
            # it until the last one retires it). The put is bounded: a
            # full inbox whose workers are already exiting another way
            # (socket error, EXIT) must not block the closer.
            while True:
                with self._cond:
                    if self._live <= 0:
                        break
                try:
                    self._inbox.put(_SENTINEL, timeout=0.05)
                    break
                except queue.Full:
                    if time.monotonic() >= deadline:
                        break
        for t in threads:
            t.join(timeout=max(0.0, deadline - time.monotonic()))
        self.fail_leftovers()
        self._closed_evt.set()
        sup = self._supervisor
        if sup is not None:
            sup.unwatch(self)

    def restart(self) -> "ServingLoop":
        """CLOSED → RUNNING with fresh threads (and a fresh inbox unless
        the inbox is externally owned). Supervisor-driven."""
        with self._cond:
            if self._state is not LoopState.CLOSED:
                raise IllegalLoopTransition(
                    f"{self.name}: restart() from {self._state.value}")
            if self._retired:
                raise IllegalLoopTransition(
                    f"{self.name}: restart() after deliberate close()")
            if self._inbox is not None and not self._external_inbox:
                self._inbox = queue.Queue(maxsize=self._inbox_maxsize)
            self._crash_exc = None
            self._crash_handled = False
            self._closer = None
            self._closed_evt = threading.Event()
            self._threads = [t for t in self._threads if t.is_alive()]
            self.generation += 1
            self.restarts += 1
            self._state = LoopState.RUNNING
            self._spawn_locked()
        return self

    # ------------------------------------------------------------- inbox
    def put(self, item: Any, timeout: Optional[float] = None) -> None:
        """Enqueue work. Raises ``LoopClosed`` once the loop is CLOSED.
        A put that races close() is recovered: if the state flipped to
        CLOSED after the enqueue, the (idempotent) leftover drain runs
        again so the item is failed, never stranded."""
        if self._inbox is None:
            raise LoopError(f"{self.name} is a tick loop (no inbox)")
        if self._state is LoopState.CLOSED:
            raise LoopClosed(f"{self.name} is closed")
        self._inbox.put(item, timeout=timeout)
        if self._state is LoopState.CLOSED:
            self.fail_leftovers()

    def get(self, timeout: Optional[float] = None) -> Any:
        """Expose the inbox to batching handlers (raises ``queue.Empty``).
        Control tokens are never returned: a handler pulling extra items
        to extend a batch must not swallow the pool's sentinel."""
        if self._inbox is None:
            raise LoopError(f"{self.name} is a tick loop (no inbox)")
        item = self._inbox.get(timeout=timeout)
        if isinstance(item, _Token):
            self._inbox.put(item)
            raise queue.Empty()
        return item

    def set_workers(self, n: int) -> int:
        """Scale the worker pool within [1, max_workers]; surplus workers
        are retired via one ``_RESIGN`` token each."""
        if self._inbox is None:
            raise LoopError(f"{self.name} is a tick loop (no pool)")
        n = max(1, min(int(n), self._max_workers))
        spawn = resign = 0
        with self._cond:
            if self._state is not LoopState.RUNNING:
                return self._workers
            self._workers = n
            if n > self._live:
                spawn = n - self._live
                for _ in range(spawn):
                    self._spawn_worker_locked()
            elif n < self._live:
                resign = self._live - n
        for _ in range(resign):
            self._inbox.put(_RESIGN)
        return n

    def fail_leftovers(self) -> int:
        """Drain the inbox, handing every non-token item to
        ``on_leftover``. Idempotent; safe from any thread once the loop
        is CLOSED (or crashing)."""
        if self._inbox is None:
            return 0
        n = 0
        while True:
            try:
                item = self._inbox.get_nowait()
            except queue.Empty:
                return n
            if isinstance(item, _Token):
                continue
            n += 1
            if self._on_leftover is not None:
                self._on_leftover(item)

    # ------------------------------------------------------ thread mains
    def _worker_main(self) -> None:
        exc: Optional[BaseException] = None
        try:
            self._consume()
        except BaseException as e:  # noqa: BLE001 - crash recording
            exc = e
        finally:
            self._retire(exc)

    def _consume(self) -> None:
        inbox = self._inbox
        head: Any = None
        while True:
            item = head if head is not None else inbox.get()
            head = None
            if item is _SENTINEL:
                chaos = self._chaos
                if chaos is not None:
                    fault = getattr(chaos, "sentinel_fault", None)
                    if fault is not None:
                        fault()
                with self._cond:
                    last = self._live <= 1
                if not last:
                    inbox.put(_SENTINEL)
                return
            if item is _RESIGN:
                return
            if self._state is LoopState.DRAINING:
                chaos = self._chaos
                if chaos is not None:
                    fault = getattr(chaos, "drain_fault", None)
                    if fault is not None:
                        fault()
            out = self._handler(item)
            if out is EXIT:
                return
            head = out

    def _tick_main(self) -> None:
        exc: Optional[BaseException] = None
        clean = False
        try:
            while True:
                if self._state is LoopState.CLOSED:
                    clean = True
                    break
                if self._state is LoopState.DRAINING:
                    chaos = self._chaos
                    if chaos is not None:
                        fault = getattr(chaos, "drain_fault", None)
                        if fault is not None:
                            fault()
                if not self._tick():
                    clean = True
                    break
            chaos = self._chaos
            if chaos is not None and clean:
                fault = getattr(chaos, "sentinel_fault", None)
                if fault is not None:
                    fault()
        except BaseException as e:  # noqa: BLE001 - crash recording
            exc = e
        finally:
            self._retire(exc)

    def _retire(self, exc: Optional[BaseException]) -> None:
        """Common worker/tick exit path: drop the live count, surface the
        exit to the owner, record a crash for the supervisor. Any
        exception-free exit (sentinel, resign, EXIT, tick False) marks
        the loop clean so the supervisor's liveness backstop never
        mistakes a deliberately retired pool for a dead one."""
        with self._cond:
            self._live -= 1
            if exc is None:
                self._clean_exit = True
            self._cond.notify_all()
        if self._on_worker_exit is not None:
            try:
                self._on_worker_exit(self, exc)
            except Exception:  # noqa: BLE001 - exit hooks must not recurse
                pass
        if exc is not None:
            self._note_crash(exc)

    def _note_crash(self, exc: BaseException) -> None:
        with self._cond:
            if self._crash_exc is None:
                self._crash_exc = exc
            sup = self._supervisor
        if sup is not None:
            sup.ping()

    def _finish_crash(self, exc: BaseException) -> bool:
        """Supervisor-driven crash completion: force CLOSED, walk any
        surviving workers out with ``_RESIGN`` (no sentinel re-put — a
        crashed producer must not shut down a healthy downstream loop),
        fail leftovers, release close() waiters. Returns False when the
        crash was already handled (idempotent)."""
        with self._cond:
            if self._crash_handled:
                return False
            self._crash_handled = True
            if self._crash_exc is None:
                self._crash_exc = exc
            already_closed = self._state is LoopState.CLOSED
            self._state = LoopState.CLOSED
            self._cond.notify_all()
            live = self._live
        if self._wake is not None:
            self._wake()
        if self._inbox is not None:
            for _ in range(max(0, live)):
                self._inbox.put(_RESIGN)
        self.fail_leftovers()
        self._closed_evt.set()
        return not already_closed

    # ------------------------------------------------------- supervision
    def _attach(self, sup: "LoopSupervisor") -> None:
        with self._cond:
            self._supervisor = sup

    def _detach(self) -> None:
        with self._cond:
            self._supervisor = None


class LoopSupervisor:
    """Watches ``ServingLoop``s for thread death and runs the uniform
    recovery contract:

    1. ``loop._finish_crash(exc)`` — force CLOSED, retire survivors,
       fail leftover inbox items (typed, via the loop's ``on_leftover``).
    2. ``on_death(loop, exc)`` — the owner fails its in-flight futures
       with ``LoopCrashed``. Returning False vetoes the restart (servers
       return False once they are deliberately closing).
    3. optional ``loop.restart()`` after exponential backoff.

    The supervisor thread copies its watch table under ``_lock`` and acts
    entirely outside it, so recovery callbacks may take server locks of
    any rank.
    """

    def __init__(self, poll_s: float = 0.05):
        self._lock = threading.Lock()
        self._watched: dict = {}     # loop -> entry dict
        self._ping = threading.Event()
        self._poll_s = poll_s
        self._stop = False
        self._thread: Optional[threading.Thread] = None
        self.recoveries = 0

    def watch(self, loop: ServingLoop, *,
              on_death: Optional[
                  Callable[[ServingLoop, BaseException], Any]] = None,
              restart: bool = False, backoff_s: float = 0.05,
              backoff_cap_s: float = 2.0) -> None:
        entry = {"on_death": on_death, "restart": restart,
                 "backoff_s": backoff_s, "backoff_cap_s": backoff_cap_s,
                 "attempts": 0, "handled_gen": -1}
        with self._lock:
            self._watched[loop] = entry
            if self._thread is None or not self._thread.is_alive():
                self._stop = False
                self._thread = threading.Thread(
                    target=self._scan_loop, daemon=True,
                    name="loop-supervisor")
                self._thread.start()
        loop._attach(self)

    def unwatch(self, loop: ServingLoop) -> None:
        with self._lock:
            self._watched.pop(loop, None)
        loop._detach()

    def ping(self) -> None:
        self._ping.set()

    def shutdown(self) -> None:
        with self._lock:
            self._stop = True
            loops = list(self._watched)
            self._watched.clear()
        for lp in loops:
            lp._detach()
        self._ping.set()

    # ------------------------------------------------------------ worker
    def _scan_loop(self) -> None:
        while True:
            self._ping.wait(self._poll_s)
            self._ping.clear()
            with self._lock:
                if self._stop:
                    return
                entries = list(self._watched.items())
            for loop, entry in entries:
                self._scan_one(loop, entry)

    def _scan_one(self, loop: ServingLoop, entry: dict) -> None:
        exc = loop.crashed
        if exc is None:
            # liveness backstop: a loop that should be running but whose
            # threads are all gone without a clean exit is dead too
            # (e.g. a worker swallowed into an uninterruptible state and
            # the interpreter reaped it).
            with loop._cond:
                stalled = (loop._state in (LoopState.RUNNING,
                                           LoopState.DRAINING)
                           and loop._threads
                           and not any(t.is_alive() for t in loop._threads)
                           and not loop._clean_exit)
            if not stalled:
                return
            exc = LoopCrashed(f"{loop.name}: loop thread died without "
                              f"a recorded exception")
        if entry["handled_gen"] >= loop.generation:
            return
        entry["handled_gen"] = loop.generation
        loop._finish_crash(exc)
        self.recoveries += 1
        verdict = None
        if entry["on_death"] is not None:
            try:
                verdict = entry["on_death"](loop, exc)
            except Exception:  # noqa: BLE001 - recovery must not die
                verdict = False
        if not entry["restart"] or verdict is False:
            return
        delay = min(entry["backoff_s"] * (2 ** entry["attempts"]),
                    entry["backoff_cap_s"])
        entry["attempts"] += 1
        time.sleep(delay)
        try:
            loop.restart()
        except IllegalLoopTransition:
            pass


_supervisor_lock = threading.Lock()
_supervisor: Optional[LoopSupervisor] = None


def supervisor() -> LoopSupervisor:
    """Process-wide ``LoopSupervisor`` singleton (lazily started)."""
    global _supervisor
    with _supervisor_lock:
        if _supervisor is None:
            _supervisor = LoopSupervisor()
        return _supervisor
