"""Early stopping over the mesh trainer (reference:
parallelism/EarlyStoppingParallelTrainer.java — the early-stopping loop with
ParallelWrapper doing each epoch's fitting)."""

from __future__ import annotations

from typing import Optional

from jax.sharding import Mesh

from deeplearning4j_tpu.earlystopping.trainer import EarlyStoppingTrainer
from deeplearning4j_tpu.parallel.trainer import ParallelWrapper


class _MeshFitAdapter:
    """Presents ParallelWrapper's round-based fit as the per-DataSet fit the
    early-stopping loop drives; buffers until a full averaging round."""

    def __init__(self, pw: ParallelWrapper):
        self.pw = pw
        self._buf: list = []
        self._expected_batch = None

    def fit(self, ds):
        import numpy as np

        b = np.asarray(ds.features).shape[0]
        if self._expected_batch is None:
            self._expected_batch = b
        if b != self._expected_batch:
            # undersized trailing minibatch: dropped, matching
            # ParallelWrapper.fit's uniform-batch filter (static XLA shapes)
            return
        self._buf.append(ds)
        need = self.pw.workers * self.pw.averaging_frequency
        if len(self._buf) >= need:
            self.pw._fit_round(self._buf[:need])
            self._buf = self._buf[need:]

    def __getattr__(self, name):
        return getattr(self.pw.net, name)


class EarlyStoppingParallelTrainer(EarlyStoppingTrainer):
    def __init__(self, config, net, train_iterator,
                 mesh: Optional[Mesh] = None, workers: Optional[int] = None,
                 averaging_frequency: int = 1, mode: str = "shared_gradients",
                 listener=None):
        pw = ParallelWrapper(net, mesh=mesh, workers=workers,
                             averaging_frequency=averaging_frequency,
                             mode=mode)
        super().__init__(config, _MeshFitAdapter(pw), train_iterator,
                         listener=listener)
        self.wrapper = pw
