"""Early stopping over the mesh trainer (reference:
parallelism/EarlyStoppingParallelTrainer.java — the early-stopping loop with
ParallelWrapper doing each epoch's fitting)."""

from __future__ import annotations

from typing import Optional

from jax.sharding import Mesh

from deeplearning4j_tpu.earlystopping.trainer import EarlyStoppingTrainer
from deeplearning4j_tpu.parallel.trainer import ParallelWrapper


class _MeshFitAdapter:
    """Presents ParallelWrapper's round-based fit as the per-DataSet fit the
    early-stopping loop drives; buffers until a full averaging round."""

    def __init__(self, pw: ParallelWrapper):
        self.pw = pw
        self._buf: list = []
        self._expected_batch = None
        # one policy carried across the per-minibatch fit calls, so skip
        # runs / recovery counters span rounds (resolved on first use)
        self._policy = None
        self._policy_src = None

    def fit(self, ds, health_guard=None):
        import numpy as np

        b = np.asarray(ds.features).shape[0]
        if self._expected_batch is None:
            self._expected_batch = b
        if b != self._expected_batch:
            # undersized trailing minibatch: dropped, matching
            # ParallelWrapper.fit's uniform-batch filter (static XLA shapes)
            return
        self._buf.append(ds)
        need = self.pw.workers * self.pw.averaging_frequency
        if len(self._buf) >= need:
            self._run_round(self._buf[:need], health_guard)
            self._buf = self._buf[need:]

    def _run_round(self, batches, health_guard):
        from deeplearning4j_tpu.optimize.health import resolve_health_policy

        pw = self.pw
        if health_guard is not self._policy_src:
            self._policy_src = health_guard
            self._policy = resolve_health_policy(health_guard)
        policy = self._policy
        # same binding dance as ParallelWrapper.fit, scoped to one round
        prev_health = getattr(pw.net, "_health", None)
        pw._policy = policy
        if policy is not None:
            policy.bind(pw.net, invalidate=pw._invalidate_programs)
            pw.net._health = policy
        try:
            pw._fit_round(batches)
        finally:
            pw._policy = None
            if policy is not None:
                pw.net._health = prev_health

    def __getattr__(self, name):
        return getattr(self.pw.net, name)


class EarlyStoppingParallelTrainer(EarlyStoppingTrainer):
    def __init__(self, config, net, train_iterator,
                 mesh: Optional[Mesh] = None, workers: Optional[int] = None,
                 averaging_frequency: int = 1, mode: str = "shared_gradients",
                 listener=None, health_guard=None):
        pw = ParallelWrapper(net, mesh=mesh, workers=workers,
                             averaging_frequency=averaging_frequency,
                             mode=mode, health_guard=health_guard)
        super().__init__(config, _MeshFitAdapter(pw), train_iterator,
                         listener=listener, health_guard=health_guard)
        self.wrapper = pw
