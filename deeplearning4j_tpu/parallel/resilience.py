"""Serving-side resilience primitives: deadlines, admission control,
retries, a circuit breaker, and a deterministic chaos injector.

The serving counterpart of ``optimize/health.py``: training self-heals on
device, and with this module the serving path (the coalescing
``ParallelInference`` server and the ``KerasBackendServer`` HTTP frontend)
degrades *typed and bounded* instead of failing open. The contract every
component here enforces is the SRE one: a submitted request either
resolves, or fails promptly with an error from the taxonomy below — it is
never left pending forever, and an overloaded server sheds load instead of
queueing it unboundedly.

The reference stack has no analog (DL4J's ParallelInference blocks callers
on an unbounded observable queue); the designs here are the standard
model-server guardrails: decorrelated-jitter backoff (the AWS architecture
blog variant), a closed -> open -> half-open breaker over a sliding outcome
window, and high-watermark admission control.

Everything in this module is host-side stdlib — no jax, no device state —
so it is reusable by any serving surface (and importable by test harnesses
without touching an accelerator).
"""

from __future__ import annotations

import random
import threading
import time
from collections import deque
from typing import Callable, Optional, Tuple

from deeplearning4j_tpu.streaming.client import StreamStalled  # noqa: F401
# StreamStalled lives with the streaming consumer (keeping streaming/
# importable without this package) but belongs to this taxonomy: re-export
# it so `resilience` names every typed serving failure.


class ResilienceError(RuntimeError):
    """Base of the typed serving-failure taxonomy. Every admitted request
    either resolves or fails with one of these subclasses (or with the
    original dispatch error once the retry budget is spent) — never
    silently dropped, never left pending."""


class DeadlineExceeded(ResilienceError):
    """The request's time budget ran out before a result was produced.
    HTTP mapping: 504."""


class ServerOverloaded(ResilienceError):
    """Admission control shed the request: the pending count was at the
    high-watermark. Raised immediately at submit — the caller is told to
    back off rather than being blocked behind an unbounded queue.
    HTTP mapping: 429."""


class CircuitOpen(ResilienceError):
    """The circuit breaker is open: recent dispatches failed above the
    threshold rate, so new work is fast-failed until a half-open probe
    succeeds. HTTP mapping: 503."""


class TransientDispatchError(ResilienceError):
    """A dispatch failure worth retrying (device hiccup, transient
    transport error). ``RetryPolicy`` retries exactly these; anything
    else propagates on the first attempt. HTTP mapping: 503 (when the
    retry budget is exhausted)."""


class ReplicaKilled(ResilienceError):
    """A model replica died mid-request (process crash, device loss, or an
    injected chaos kill). Non-retryable at the replica level — the replica
    is gone — but the fleet router re-dispatches the victim requests to a
    surviving replica, so callers behind a ``ReplicaFleet`` normally never
    see this. HTTP mapping: 503 (when it does escape)."""


class ReplicaUnavailable(ResilienceError):
    """No replica can take the request right now: every fleet member is
    dead, restarting, draining, or breaker-open. Raised at submit so the
    caller sheds load instead of queueing behind a fleet that cannot make
    progress. HTTP mapping: 503."""


class Deadline:
    """Per-request time budget with remaining-time propagation.

    Created once at admission; every later stage (queue pickup, batch
    assembly, padding, each retry attempt) asks ``remaining()`` instead of
    re-deriving its own budget, so the request's clock never resets as it
    moves through the pipeline and an expired request is failed *before*
    a device program is wasted on it."""

    __slots__ = ("expires_at", "_clock")

    def __init__(self, budget_s: float, *,
                 clock: Callable[[], float] = time.monotonic):
        self._clock = clock
        self.expires_at = clock() + float(budget_s)

    def remaining(self) -> float:
        """Seconds left in the budget (<= 0 once expired)."""
        return self.expires_at - self._clock()

    def expired(self) -> bool:
        return self.remaining() <= 0


class RetryPolicy:
    """Capped exponential backoff with decorrelated jitter, retrying ONLY
    transient errors.

    ``sleep_{i+1} ~ U[base_s, 3 * sleep_i]`` capped at ``cap_s`` — the
    decorrelated variant spreads concurrent retriers apart instead of
    synchronizing them into retry storms. Deterministic under ``seed``;
    ``sleep`` is injectable so tests run at full speed."""

    def __init__(self, max_attempts: int = 3, base_s: float = 0.005,
                 cap_s: float = 0.25,
                 retry_on: Tuple[type, ...] = (TransientDispatchError,),
                 seed: Optional[int] = None,
                 sleep: Callable[[float], None] = time.sleep):
        self.max_attempts = max(1, int(max_attempts))
        self.base_s = float(base_s)
        self.cap_s = float(cap_s)
        self.retry_on = tuple(retry_on)
        self._rng = random.Random(seed)
        self._sleep = sleep
        self._lock = threading.Lock()

    def backoff_s(self, previous: float) -> float:
        with self._lock:  # one rng shared by concurrent dispatch threads
            return min(self.cap_s,
                       self._rng.uniform(self.base_s,
                                         max(self.base_s, 3.0 * previous)))

    def call(self, fn: Callable, *args,
             deadline: Optional[Deadline] = None,
             on_retry: Optional[Callable[[int, Exception], None]] = None):
        """Run ``fn(*args)`` retrying transient failures until the attempt
        budget — or the request's deadline — runs out. A backoff that the
        deadline cannot cover gives up immediately (re-raising the
        transient error) instead of sleeping past the budget."""
        delay = self.base_s
        for attempt in range(1, self.max_attempts + 1):
            try:
                return fn(*args)
            except self.retry_on:
                if attempt >= self.max_attempts:
                    raise
                delay = self.backoff_s(delay)
                if deadline is not None and deadline.remaining() <= delay:
                    raise
                if on_retry is not None:
                    on_retry(attempt, None)
                self._sleep(delay)


class CircuitBreaker:
    """closed -> open (failure rate over a sliding window crosses the
    threshold) -> half-open probe after ``reset_timeout_s`` -> closed on
    probe success, reopened on probe failure.

    ``allow()`` is the admission-side gate (an open breaker fast-fails new
    submits); ``record_success``/``record_failure`` are fed per dispatch
    attempt. The clock is injectable so state transitions are testable
    without real waiting."""

    CLOSED = "closed"
    OPEN = "open"
    HALF_OPEN = "half_open"

    def __init__(self, failure_threshold: float = 0.5, window: int = 16,
                 min_calls: int = 8, reset_timeout_s: float = 5.0,
                 half_open_probes: int = 1,
                 clock: Callable[[], float] = time.monotonic):
        self.failure_threshold = float(failure_threshold)
        self.min_calls = max(1, int(min_calls))
        self.reset_timeout_s = float(reset_timeout_s)
        self.half_open_probes = max(1, int(half_open_probes))
        self._clock = clock
        self._outcomes: deque = deque(maxlen=max(1, int(window)))
        self._lock = threading.Lock()
        self._state = self.CLOSED
        self._opened_at = 0.0
        self._probes = 0
        self._half_open_at = 0.0
        #: times the breaker tripped open (monotone counter, for stats())
        self.open_count = 0

    @property
    def state(self) -> str:
        with self._lock:
            return self._current_state()

    def _current_state(self) -> str:
        # lock held; OPEN decays to HALF_OPEN once the reset timeout passes
        now = self._clock()
        if (self._state == self.OPEN
                and now - self._opened_at >= self.reset_timeout_s):
            self._state = self.HALF_OPEN
            self._probes = 0
            self._half_open_at = now
        elif (self._state == self.HALF_OPEN
                and now - self._half_open_at >= self.reset_timeout_s):
            # probes that never reported an outcome (e.g. the probe request
            # expired before dispatch) must not wedge the breaker in a
            # probe-exhausted half-open state: replenish periodically
            self._probes = 0
            self._half_open_at = now
        return self._state

    def allow(self) -> bool:
        """May new work enter? CLOSED: yes. OPEN: no (fast-fail).
        HALF_OPEN: up to ``half_open_probes`` probes, then no."""
        with self._lock:
            st = self._current_state()
            if st == self.CLOSED:
                return True
            if st == self.OPEN:
                return False
            if self._probes < self.half_open_probes:
                self._probes += 1
                return True
            return False

    def record_success(self) -> None:
        with self._lock:
            if self._current_state() == self.HALF_OPEN:
                # the probe came back healthy: close and start fresh
                self._state = self.CLOSED
                self._outcomes.clear()
            else:
                self._outcomes.append(True)

    def record_failure(self) -> None:
        with self._lock:
            if self._current_state() == self.HALF_OPEN:
                self._trip()  # the probe failed: straight back to open
                return
            self._outcomes.append(False)
            n = len(self._outcomes)
            failures = n - sum(self._outcomes)
            if n >= self.min_calls and failures / n >= self.failure_threshold:
                self._trip()

    def _trip(self) -> None:
        # lock held
        self._state = self.OPEN
        self._opened_at = self._clock()
        self._outcomes.clear()
        self.open_count += 1


class AdmissionController:
    """High-watermark load shedding: beyond ``max_pending`` in-flight
    requests, ``acquire()`` raises ``ServerOverloaded`` immediately
    instead of blocking the caller. Also the server's accepted/rejected/
    pending bookkeeping — release exactly once per acquire (the serving
    layers do it from a future done-callback, which covers every
    resolution path)."""

    def __init__(self, max_pending: int = 256):
        self.max_pending = max(1, int(max_pending))
        self._lock = threading.Lock()
        self.pending = 0
        self.accepted = 0
        self.rejected = 0

    def acquire(self) -> None:
        with self._lock:
            if self.pending >= self.max_pending:
                self.rejected += 1
                raise ServerOverloaded(
                    f"{self.pending} requests pending, at the "
                    f"max_pending={self.max_pending} high-watermark")
            self.pending += 1
            self.accepted += 1

    def release(self) -> None:
        with self._lock:
            self.pending -= 1


class ChaosPolicy:
    """Deterministic, seedable fault injector for tests and the chaos
    bench — wraps a dispatch callable to inject latency, transient errors
    (retryable), and hard errors, at independent per-call rates drawn from
    one seeded rng. All rates default to 0 and nothing in the production
    path constructs one: chaos only exists where a test or bench passes it
    in explicitly.

    Replica-targeted fault modes (for ``ReplicaFleet`` drills; give each
    replica its own policy with a distinct seed to target them
    independently):

    - ``kill_rate``: raise ``ReplicaKilled`` — a hard, non-retryable
      replica death. Inside a ``GenerationServer`` dispatch this takes the
      hard-fault path (every in-flight request on the replica fails typed),
      which is exactly the signal the fleet treats as replica death.
    - ``stall_rate``/``stall_s``: the dispatch freezes for ``stall_s``
      before running — a straggler replica, the hedging target.
    - ``slow_rate``/``slow_factor``: the dispatch runs, then the wrapper
      sleeps ``(slow_factor - 1) x`` the measured run time — slow-decode,
      degrading throughput without ever failing.

    The replica-mode randoms are drawn only when one of the replica rates
    is non-zero, so pre-existing seeds reproduce the same latency/error
    sequences as before.

    Handoff fault modes (for the KV-snapshot migration drills in
    ``parallel/handoff.py``; injected via ``handoff_fault()`` from the
    snapshot path, never from ``wrap()``):

    - ``snapshot_corrupt_rate``: the snapshot about to ship gets one
      payload bit flipped after its checksum was computed, so the
      adopter's ``verify()`` fails and the fleet falls back to token-0
      regeneration.
    - ``handoff_stall_rate``/``handoff_stall_s``: the snapshot path
      freezes for ``handoff_stall_s`` — a slow migration wire.
    - ``handoff_drop_rate``: the transfer vanishes in flight — the
      snapshot is never published/shipped, so the consumer sees a typed
      ``SnapshotUnavailable`` failure and re-runs the work elsewhere.
    - ``handoff_truncate_rate``: the transfer is cut short — the wire
      tail reads back as zeros, so the adopter's checksum ``verify()``
      fails and it falls back to token-0 regeneration.

    ``handoff_fault()``/``handoff_fault_mode()`` draw from the shared
    rng only when one of the handoff rates is non-zero, so legacy
    wrap() sequences are reproduced bit-for-bit even on servers that
    call it every loop; the new drop/truncate rates gate the draw the
    same way, so pre-existing handoff fault sequences (corrupt/stall
    only) also stay pinned.

    Shutdown-phase fault modes (for the ``ServingLoop`` lifecycle
    drills in ``parallel/runtime.py``; injected via ``drain_fault()``
    from a DRAINING loop's item/tick path and ``sentinel_fault()``
    from the sentinel/clean-exit path, never from ``wrap()``):

    - ``kill_during_drain_rate``: raise ``LoopKilled`` (a
      ``BaseException``, so server loop bodies that catch ``Exception``
      to keep serving cannot swallow it) — the loop thread dies
      mid-drain and the supervisor must recover every in-flight future.
    - ``stall_sentinel_rate``/``stall_sentinel_s``: the worker freezes
      for ``stall_sentinel_s`` while retiring on the shutdown sentinel
      — ``close(timeout)`` must give up on the join, fail leftovers,
      and return without stranding a future.

    Both draws are gated on their own non-zero rates, so every legacy
    seeded sequence (wrap, replica, handoff) stays pinned.

    Network fault modes (for the cross-host federation drills in
    ``parallel/federation.py``; injected from the framed-RPC link path
    via ``net_connect_fault()`` per outbound connect and
    ``net_fault_mode()`` per frame sent, never from ``wrap()``):

    - ``conn_refused_rate``: the outbound connect attempt raises
      ``ConnectionRefusedError`` — the host's listener is gone (or a
      firewall ate the SYN); the router's per-host RetryPolicy and
      breaker absorb it.
    - ``partition_rate``/``partition_s``: the link becomes unreachable
      for a ``partition_s``-second window (monotonic clock) — every
      send inside the window fails ``TransientDispatchError`` without
      touching the socket, the CI stand-in for a network partition
      that heals. ``net_partitioned()`` reports the window state.
    - ``slow_link_factor``: every frame pays a deterministic
      serialization delay of ``(factor - 1) x nbytes / 100 MB/s`` — a
      degraded NIC. No rng draw at all (factor 1.0 = off), so it can
      never perturb a seeded sequence.
    - ``frame_corrupt_rate``: one bit of the frame body is flipped
      after the length prefix is written, so the receiver's framed
      reader rejects it typed (``FederationProtocolError`` /
      checksum failure) instead of trusting damaged bytes.

    ``net_connect_fault()`` and ``net_fault_mode()`` each draw from
    the shared rng only when their own rates are non-zero (the
    partition/corrupt pair shares one stacked-threshold draw, mutually
    exclusive per frame like the replica modes), so all legacy fault
    sequences — wrap, replica, handoff, shutdown — replay pinned."""

    #: nominal healthy link bandwidth the ``slow_link_factor`` delay is
    #: computed against (bytes/second)
    LINK_BYTES_PER_S = 100e6

    def __init__(self, seed: int = 0, transient_rate: float = 0.0,
                 hard_rate: float = 0.0, latency_s: float = 0.0,
                 latency_rate: float = 0.0,
                 kill_rate: float = 0.0,
                 stall_rate: float = 0.0, stall_s: float = 0.0,
                 slow_rate: float = 0.0, slow_factor: float = 1.0,
                 snapshot_corrupt_rate: float = 0.0,
                 handoff_stall_rate: float = 0.0,
                 handoff_stall_s: float = 0.0,
                 handoff_drop_rate: float = 0.0,
                 handoff_truncate_rate: float = 0.0,
                 kill_during_drain_rate: float = 0.0,
                 stall_sentinel_rate: float = 0.0,
                 stall_sentinel_s: float = 0.0,
                 conn_refused_rate: float = 0.0,
                 partition_rate: float = 0.0,
                 partition_s: float = 0.0,
                 slow_link_factor: float = 1.0,
                 frame_corrupt_rate: float = 0.0,
                 sleep: Callable[[float], None] = time.sleep):
        self.transient_rate = float(transient_rate)
        self.hard_rate = float(hard_rate)
        self.latency_s = float(latency_s)
        self.latency_rate = float(latency_rate)
        self.kill_rate = float(kill_rate)
        self.stall_rate = float(stall_rate)
        self.stall_s = float(stall_s)
        self.slow_rate = float(slow_rate)
        self.slow_factor = float(slow_factor)
        self.snapshot_corrupt_rate = float(snapshot_corrupt_rate)
        self.handoff_stall_rate = float(handoff_stall_rate)
        self.handoff_stall_s = float(handoff_stall_s)
        self.handoff_drop_rate = float(handoff_drop_rate)
        self.handoff_truncate_rate = float(handoff_truncate_rate)
        self.kill_during_drain_rate = float(kill_during_drain_rate)
        self.stall_sentinel_rate = float(stall_sentinel_rate)
        self.stall_sentinel_s = float(stall_sentinel_s)
        self.conn_refused_rate = float(conn_refused_rate)
        self.partition_rate = float(partition_rate)
        self.partition_s = float(partition_s)
        self.slow_link_factor = float(slow_link_factor)
        self.frame_corrupt_rate = float(frame_corrupt_rate)
        self._partition_until = 0.0
        self._sleep = sleep
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self.injected_transient = 0
        self.injected_hard = 0
        self.injected_latency = 0
        self.injected_kill = 0
        self.injected_stall = 0
        self.injected_slow = 0
        self.injected_snapshot_corrupt = 0
        self.injected_handoff_stall = 0
        self.injected_handoff_drop = 0
        self.injected_handoff_truncate = 0
        self.injected_drain_kill = 0
        self.injected_sentinel_stall = 0
        self.injected_conn_refused = 0
        self.injected_partition = 0
        self.injected_slow_link = 0
        self.injected_frame_corrupt = 0

    def net_connect_fault(self) -> None:
        """One seeded draw per outbound connect attempt on a federation
        link (and only when ``conn_refused_rate`` is non-zero, so every
        legacy seeded sequence stays pinned). On a hit, raises
        ``ConnectionRefusedError`` before the socket is touched — the
        same error a dead listener produces, so the router's retry /
        breaker / reconnect machinery cannot tell injection from the
        real thing."""
        if not self.conn_refused_rate:
            return
        with self._lock:
            hit = self._rng.random() < self.conn_refused_rate
            if hit:
                self.injected_conn_refused += 1
        if hit:
            raise ConnectionRefusedError(
                "chaos: connection refused by injected fault")

    def net_partitioned(self) -> bool:
        """True while the link is inside an injected partition window
        (armed by a ``net_fault_mode()`` partition hit)."""
        with self._lock:
            until = self._partition_until
        return time.monotonic() < until

    def net_fault_mode(self, nbytes: int = 0) -> Optional[str]:
        """One seeded draw per frame sent on a federation link, gated
        on the partition/corrupt rates being non-zero so all legacy
        sequences replay pinned. The ``slow_link_factor`` delay is
        deterministic (no draw): ``(factor - 1) x nbytes`` over a
        nominal 100 MB/s link, applied before the draw. Returns the
        injected mode — ``"partition"`` (window armed; the caller must
        fail the send without touching the socket) or ``"corrupt"``
        (the caller flips one bit of the frame body) — or None. The
        modes share one stacked-threshold draw, mutually exclusive per
        frame like the replica modes."""
        if self.slow_link_factor > 1.0 and nbytes > 0:
            with self._lock:
                self.injected_slow_link += 1
            self._sleep((self.slow_link_factor - 1.0)
                        * nbytes / self.LINK_BYTES_PER_S)
        if not (self.partition_rate or self.frame_corrupt_rate):
            return None
        with self._lock:
            r = self._rng.random()
            t = self.partition_rate
            part = r < t
            t += self.frame_corrupt_rate
            corrupt = not part and r < t
            if part:
                self.injected_partition += 1
                self._partition_until = time.monotonic() + self.partition_s
            if corrupt:
                self.injected_frame_corrupt += 1
        if part:
            return "partition"
        if corrupt:
            return "corrupt"
        return None

    def drain_fault(self) -> None:
        """One seeded draw per item/tick handled while the hosting
        ``ServingLoop`` is DRAINING (and only when the rate is non-zero,
        so every legacy seeded sequence stays pinned). On a hit, raises
        ``LoopKilled`` — a ``BaseException`` that escapes the server
        loop bodies' ``except Exception`` recovery and takes the loop
        thread down mid-drain, which is exactly the failure the
        supervisor contract must absorb."""
        if not self.kill_during_drain_rate:
            return
        with self._lock:
            hit = self._rng.random() < self.kill_during_drain_rate
            if hit:
                self.injected_drain_kill += 1
        if hit:
            from deeplearning4j_tpu.parallel.runtime import LoopKilled
            raise LoopKilled("chaos: loop thread killed mid-drain")

    def sentinel_fault(self) -> None:
        """One seeded draw per worker retiring on the shutdown sentinel
        (or per tick loop exiting cleanly); gated on its own non-zero
        rate so legacy sequences stay pinned. On a hit, the retiring
        thread stalls for ``stall_sentinel_s`` — ``close(timeout)``
        must not hang on the join and must still fail every leftover."""
        if not self.stall_sentinel_rate:
            return
        with self._lock:
            hit = self._rng.random() < self.stall_sentinel_rate
            if hit:
                self.injected_sentinel_stall += 1
        if hit:
            self._sleep(self.stall_sentinel_s)

    def handoff_fault(self) -> bool:
        """Legacy boolean form of ``handoff_fault_mode()``: returns True
        iff the snapshot should be corrupted (the only mode the PR-11
        consumers knew). Same single draw, same counters."""
        return self.handoff_fault_mode() == "corrupt"

    def handoff_fault_mode(self) -> Optional[str]:
        """One seeded draw per snapshot shipped (and only when a handoff
        rate is non-zero, so wrap() sequences stay pinned — and legacy
        corrupt/stall thresholds stay pinned when the new rates are 0).
        Performs the ``handoff_stall`` sleep itself, outside the rng
        lock. Returns the injected fault mode — ``"corrupt"``,
        ``"drop"``, or ``"truncate"`` — or None (a stall delays the
        transfer but does not damage it). The modes are mutually
        exclusive per draw, stacked corrupt-then-stall-then-drop-then-
        truncate like the replica modes."""
        if not (self.snapshot_corrupt_rate or self.handoff_stall_rate
                or self.handoff_drop_rate or self.handoff_truncate_rate):
            return None
        with self._lock:
            r = self._rng.random()
            t = self.snapshot_corrupt_rate
            corrupt = r < t
            t += self.handoff_stall_rate
            stall = not corrupt and r < t
            t += self.handoff_drop_rate
            drop = not (corrupt or stall) and r < t
            t += self.handoff_truncate_rate
            truncate = not (corrupt or stall or drop) and r < t
            if corrupt:
                self.injected_snapshot_corrupt += 1
            if stall:
                self.injected_handoff_stall += 1
            if drop:
                self.injected_handoff_drop += 1
            if truncate:
                self.injected_handoff_truncate += 1
        if stall:
            self._sleep(self.handoff_stall_s)
        if corrupt:
            return "corrupt"
        if drop:
            return "drop"
        if truncate:
            return "truncate"
        return None

    def wrap(self, fn: Callable) -> Callable:
        """The chaotic twin of ``fn``: same signature, same result, but
        each call may first sleep and/or raise per the configured rates."""

        def chaotic(*args, **kwargs):
            with self._lock:  # one rng, many dispatch threads
                r_latency = self._rng.random()
                r_error = self._rng.random()
                inject_latency = (self.latency_rate
                                  and r_latency < self.latency_rate)
                inject_hard = self.hard_rate and r_error < self.hard_rate
                inject_transient = (self.transient_rate and not inject_hard
                                    and r_error < (self.hard_rate
                                                   + self.transient_rate))
                inject_kill = inject_stall = inject_slow = False
                if self.kill_rate or self.stall_rate or self.slow_rate:
                    # stacked thresholds on one extra draw: at most one
                    # replica-targeted fault per call, mutually exclusive
                    r_rep = self._rng.random()
                    inject_kill = r_rep < self.kill_rate
                    inject_stall = (not inject_kill
                                    and r_rep < (self.kill_rate
                                                 + self.stall_rate))
                    inject_slow = (not (inject_kill or inject_stall)
                                   and r_rep < (self.kill_rate
                                                + self.stall_rate
                                                + self.slow_rate))
                if inject_latency:
                    self.injected_latency += 1
                if inject_hard:
                    self.injected_hard += 1
                if inject_transient:
                    self.injected_transient += 1
                if inject_kill:
                    self.injected_kill += 1
                if inject_stall:
                    self.injected_stall += 1
                if inject_slow:
                    self.injected_slow += 1
            if inject_latency:
                self._sleep(self.latency_s)
            if inject_stall:
                self._sleep(self.stall_s)
            if inject_kill:
                raise ReplicaKilled("chaos: replica killed")
            if inject_hard:
                raise RuntimeError("chaos: injected hard fault")
            if inject_transient:
                raise TransientDispatchError("chaos: injected transient "
                                             "fault")
            if inject_slow:
                t0 = time.monotonic()
                out = fn(*args, **kwargs)
                if self.slow_factor > 1.0:
                    self._sleep((self.slow_factor - 1.0)
                                * (time.monotonic() - t0))
                return out
            return fn(*args, **kwargs)

        return chaotic
