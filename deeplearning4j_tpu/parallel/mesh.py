"""Device-mesh helpers.

The reference pins model replicas to devices round-robin
(ParallelWrapper.java:148-245, trainer/DefaultTrainer.java device affinity);
here device placement is a jax.sharding.Mesh and XLA lays out the collectives
over ICI. One axis name is used throughout the data-parallel stack: ``data``.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

DATA_AXIS = "data"
MODEL_AXIS = "model"


#: True when ``jax.shard_map`` (the VMA-tracking rewrite) is in use. The two
#: implementations transpose replicated outputs differently: VMA hands every
#: model-axis copy's cotangent to the psum transpose (callers must rescale),
#: while the legacy ``check_rep`` tracker dedups them itself.
SHARD_MAP_VMA = hasattr(jax, "shard_map")


def shard_map_compat(fn, *, mesh, in_specs, out_specs, check=None):
    """``jax.shard_map`` across jax versions: jax >= 0.6 exposes it at top
    level with the ``check_vma`` keyword; older releases keep it in
    ``jax.experimental.shard_map`` spelled ``check_rep``. ``check=None``
    keeps the library default (checking ON)."""
    try:
        sm = jax.shard_map
        kw = {} if check is None else {"check_vma": check}
    except AttributeError:
        from jax.experimental.shard_map import shard_map as sm
        kw = {} if check is None else {"check_rep": check}
    return sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw)


class MeshGeometryError(ValueError):
    """Typed, loud mesh-geometry failure: the requested tensor-parallel
    degree does not divide the device count, or (raised at pool-build
    time by the serving layer) the model's head count. A plain
    ``ValueError`` subclass so legacy ``except ValueError`` callers keep
    working, but catchable on its own by fleet factories that want to
    fall back to a smaller tp."""


def model_mesh(tp: int, devices=None) -> Mesh:
    """1-D head-parallel (tensor-parallel) mesh over ``tp`` devices on
    the ``model`` axis — the mesh the sharded paged decode path runs
    over. Validation is loud and typed (``MeshGeometryError``): a silent
    fallback to fewer chips would change the page budget the server
    admitted against."""
    if devices is None:
        devices = jax.devices()
    if tp < 1:
        raise MeshGeometryError(f"tensor-parallel degree must be >= 1, got {tp}")
    if tp > len(devices):
        raise MeshGeometryError(
            f"tensor-parallel degree {tp} exceeds the {len(devices)} "
            "available devices")
    if len(devices) % tp != 0:
        raise MeshGeometryError(
            f"device count {len(devices)} is not divisible by tp={tp}: "
            "replica groups would overlap — pass an explicit device "
            "subset instead")
    return Mesh(np.array(devices[:tp]), (MODEL_AXIS,))


def data_mesh(num_devices: Optional[int] = None, devices=None) -> Mesh:
    """1-D data-parallel mesh over the first ``num_devices`` devices (default all)."""
    if devices is None:
        devices = jax.devices()
    if num_devices is not None:
        if num_devices > len(devices):
            raise ValueError(
                f"Requested {num_devices} devices but only {len(devices)} present")
        devices = devices[:num_devices]
    return Mesh(np.array(devices), (DATA_AXIS,))


def data_model_mesh(data: int, model: int, devices=None) -> Mesh:
    """2-D mesh: ``data`` x ``model`` axes (DP x TP)."""
    if devices is None:
        devices = jax.devices()
    n = data * model
    if n > len(devices):
        raise MeshGeometryError(
            f"Mesh {data}x{model} needs {n} devices, have {len(devices)}")
    return Mesh(np.array(devices[:n]).reshape(data, model), (DATA_AXIS, MODEL_AXIS))
