"""ReplicaFleet: a health-routed front end over N independent model
replicas with one ``submit() -> Future`` surface.

Data-parallel *serving*, the counterpart of the training-side scale-out in
``parallel/mesh.py``/``pipeline.py`` and the reproduction of the reference
stack's layer-4 reason-to-exist (ParallelWrapper / parameter-server
replicas): one sick or crashed replica sheds load into the rest of the
fleet instead of taking the service down.

Topology::

    submit() -> Future
        |
    ReplicaFleet ------------- monitor thread (redispatch, hedging,
        |   routing: weighted     supervised restart w/ backoff)
        |   least-loaded over
        |   healthy replicas
        +-- replica 0: CircuitBreaker + AdmissionController + server
        +-- replica 1: CircuitBreaker + AdmissionController + server
        +-- ...            (GenerationServer or ParallelInference)

Invariants:

- **Zero lost futures across replica death.** Every accepted request
  either resolves with a result or fails with a typed error from the
  ``resilience`` taxonomy. When a replica dies mid-request (chaos kill,
  crash, abrupt close), its in-flight and queued requests are re-submitted
  to a surviving replica. Because generation sampling derives every
  token's key from ``fold_in(PRNGKey(seed), token_index)`` — never from
  server state — a re-dispatched request regenerates the *bit-exact* same
  completion on any replica.
- **Lock order.** Replica servers invoke our completion callbacks while
  holding their own internal locks, so the only permitted order is
  ``server lock -> fleet._cond``. The fleet therefore never calls into a
  replica server (submit/drain/close/stats) while holding ``_cond``; all
  re-dispatch, hedging, and restart work is done by the monitor thread
  outside the lock.
- **Typed load shedding at submit.** A fresh submit with no routable
  replica fails fast — ``ReplicaUnavailable`` when every replica is
  dead/restarting/draining, ``CircuitOpen`` when the survivors' breakers
  are all open, ``ServerOverloaded`` when every replica rejected the
  request at admission — rather than queueing behind a fleet that cannot
  make progress. Only *accepted* work is parked for re-dispatch.

Hedging (optional): when a request's newest attempt has been running
longer than ``hedge_after_s``, the monitor launches a duplicate on a
different healthy replica; the first result wins and the loser is
cancelled. This bounds straggler-replica tail latency at the cost of
duplicated work.

Disaggregated tiers (optional, ``roles=``): DistServe/Splitwise-style
prefill/decode separation behind the same ``submit() -> Future``. A
fresh request routes to a prefill-capable replica, which runs chunked
wave prefill and resolves the attempt with a **KVSnapshot** instead of
tokens; the fleet stages the snapshot and re-routes it onto the decode
tier, where ``adopt_request`` resumes it at its exact stream position.
The caller's future only ever resolves with final tokens. TTFT (submit
-> first prefilled token) and inter-token latency land in separate
registry histograms (``fleet_ttft_ms`` / ``fleet_itl_ms``). When the
decode tier has no READY replica the fleet enters **degraded mode** —
co-located serving on the prefill tier (fresh submits are pinned
``export_kv=False``, staged snapshots adopt in place) — and recovers
automatically when a decode-capable replica heals.
"""

from __future__ import annotations

import functools
import logging
import threading
import time
from collections import deque
from concurrent.futures import Future
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from deeplearning4j_tpu.metrics.registry import MetricsRegistry
from deeplearning4j_tpu.parallel.handoff import KVSnapshot, SnapshotError
from deeplearning4j_tpu.parallel.resilience import (
    AdmissionController, CircuitBreaker, CircuitOpen, Deadline,
    DeadlineExceeded, ReplicaKilled, ReplicaUnavailable, ResilienceError,
    ServerOverloaded)
from deeplearning4j_tpu.parallel.runtime import (LoopCrashed, LoopState,
                                                 ServingLoop, supervisor)

log = logging.getLogger(__name__)

# Replica lifecycle: SPAWNING -> WARMING -> READY -> (DRAINING -> RETIRED
# | DEAD -> SPAWNING ...). Only READY replicas take traffic; DEAD ones are
# respawned by the monitor after their backoff; RETIRED ones never return.
SPAWNING = "spawning"
WARMING = "warming"
READY = "ready"
DRAINING = "draining"
DEAD = "dead"
RETIRED = "retired"

_EWMA_FLOOR_MS = 0.5  # score floor so a fresh replica isn't infinitely hot


def device_groups(n_groups: int, tp: int, devices=None) -> list:
    """Partition the device pool into ``n_groups`` disjoint lists of
    ``tp`` devices — one tensor-parallel replica group per fleet
    replica. The factory idiom::

        groups = device_groups(2, tp=4)
        fleet = ReplicaFleet(lambda rid: GenerationServer(
            net, vocab, mesh=model_mesh(tp, devices=groups[rid % 2])),
            replicas=2)

    Groups are disjoint by construction so two replicas never contend
    for a chip; validation is loud (``MeshGeometryError``) because a
    short group would silently shrink the page budget the replica
    admits against."""
    import jax

    from deeplearning4j_tpu.parallel.mesh import MeshGeometryError

    if devices is None:
        devices = jax.devices()
    if n_groups < 1 or tp < 1:
        raise MeshGeometryError(
            f"need n_groups >= 1 and tp >= 1, got {n_groups}x{tp}")
    need = n_groups * tp
    if need > len(devices):
        raise MeshGeometryError(
            f"{n_groups} replica groups x tp={tp} needs {need} devices, "
            f"have {len(devices)}")
    return [list(devices[g * tp:(g + 1) * tp]) for g in range(n_groups)]


class _Replica:
    """Mutable per-replica record. No lock of its own — every field is
    read and written only under the owning fleet's ``_cond`` (``server``,
    ``rid``, ``generation``, ``breaker``, ``admission`` are written once
    at construction and safe to read anywhere)."""

    __slots__ = ("rid", "generation", "server", "breaker", "admission",
                 "role", "state", "inflight", "ewma_ms", "fail_ewma",
                 "restarts", "spawn_failures", "backoff_s", "restart_at",
                 "dispatched", "completed", "failed", "rejected",
                 "prior_trips")

    def __init__(self, rid: int, generation: int, server: Any,
                 breaker: CircuitBreaker, admission: AdmissionController,
                 backoff_s: float, role: str = "unified"):
        self.rid = rid
        self.generation = generation
        self.server = server
        self.breaker = breaker
        self.admission = admission
        self.role = role
        self.state = READY
        self.inflight = 0
        self.ewma_ms = 0.0
        self.fail_ewma = 0.0
        self.restarts = 0
        self.spawn_failures = 0
        self.backoff_s = backoff_s
        self.restart_at = 0.0
        self.dispatched = 0
        self.completed = 0
        self.failed = 0
        self.rejected = 0
        self.prior_trips = 0  # breaker trips accumulated by retired breakers


def _score(r: _Replica) -> float:
    """Weighted least-loaded health score — lower routes first. Queue
    depth multiplies expected latency (EWMA); the recent-failure EWMA
    inflates the score so a flapping replica cools off even while its
    breaker is still closed."""
    ewma = r.ewma_ms if r.ewma_ms > _EWMA_FLOOR_MS else _EWMA_FLOOR_MS
    return (r.inflight + 1.0) * ewma * (1.0 + 8.0 * r.fail_ewma)


class _FleetRequest:
    """One accepted request: the original call (so a re-dispatch replays
    it identically — the fold_in key schedule then makes the regenerated
    completion bit-exact) plus routing state. Mutable fields are guarded
    by the fleet's ``_cond``."""

    __slots__ = ("args", "kwargs", "deadline", "future", "resolved",
                 "active", "tried", "attempts", "hedges", "t_dispatch",
                 "last_error", "snapshot", "t_submit", "t_first", "tier")

    def __init__(self, args: tuple, kwargs: dict,
                 deadline: Optional[Deadline], future: Future,
                 tier: Optional[str] = None):
        self.args = args
        self.kwargs = kwargs
        self.deadline = deadline
        self.future = future
        self.tier = tier  # role-pinned routing (RAG knn/generate tiers)
        self.t_submit = time.monotonic()
        self.t_first = 0.0  # when the fleet first saw a token (TTFT)
        self.resolved = False
        self.active: Dict[int, Future] = {}  # rid -> in-flight inner future
        self.tried: set = set()
        self.attempts = 0
        self.hedges = 0
        self.t_dispatch = 0.0
        self.last_error: Optional[BaseException] = None
        # newest KV snapshot harvested off a failed attempt's future:
        # the next dispatch ADOPTS it (resume at position N) instead of
        # regenerating from token 0
        self.snapshot = None


class ReplicaFleet:
    """Route ``submit()`` traffic over ``replicas`` independent servers
    built by ``factory(rid)`` — anything with the serving contract
    ``submit(*args, deadline_s=..., **kwargs) -> Future``, ``drain``,
    ``close``, ``stats`` (``GenerationServer`` and ``ParallelInference``
    both qualify).

    ``hedge_after_s`` enables straggler hedging; ``restart=False``
    disables supervised restart (dead replicas stay dead); ``warmup`` is
    an optional callable run on every freshly spawned server before it
    takes traffic (e.g. a canary request that pre-compiles programs).

    ``roles`` (one of ``"prefill"``/``"decode"``/``"unified"`` per
    replica, rid-indexed) turns on disaggregated tier routing — see the
    module docstring. The factory must build a server matching the
    declared role (``GenerationServer(role=...)``); supervised restart
    rebuilds the same rid with the same role.
    """

    def __init__(self, factory: Callable[[int], Any], replicas: int = 2, *,
                 max_pending: int = 256, replica_max_pending: int = 64,
                 hedge_after_s: Optional[float] = None, max_hedges: int = 1,
                 max_redispatch: Optional[int] = None,
                 restart: bool = True, restart_backoff_s: float = 0.05,
                 restart_backoff_cap_s: float = 2.0,
                 warmup: Optional[Callable[[Any], None]] = None,
                 breaker_factory: Optional[Callable[[], CircuitBreaker]]
                 = None,
                 health_alpha: float = 0.25, tick_s: float = 0.005,
                 registry: Optional[MetricsRegistry] = None,
                 roles: Optional[Sequence[str]] = None,
                 chaos: Any = None):
        if int(replicas) < 1:
            raise ValueError("need at least one replica")
        if roles is not None:
            roles = tuple(roles)
            if len(roles) != int(replicas):
                raise ValueError(
                    f"roles must name one role per replica "
                    f"({int(replicas)}), got {len(roles)}")
            bad = sorted({x for x in roles
                          if x not in ("unified", "prefill", "decode",
                                       "knn", "generate")})
            if bad:
                raise ValueError(f"unknown replica roles {bad!r}")
            if any(x in ("prefill", "decode") for x in roles):
                if not any(x in ("prefill", "unified") for x in roles):
                    raise ValueError("a tiered fleet needs at least one "
                                     "prefill-capable replica")
                if not any(x in ("decode", "unified") for x in roles):
                    raise ValueError("a tiered fleet needs at least one "
                                     "decode-capable replica")
        self._roles = roles
        self._factory = factory
        self._warmup = warmup
        self._breaker_factory = breaker_factory
        self._replica_max_pending = int(replica_max_pending)
        self._hedge_after_s = (None if hedge_after_s is None
                               else float(hedge_after_s))
        self._max_hedges = max(0, int(max_hedges))
        self._max_redispatch = (None if max_redispatch is None
                                else int(max_redispatch))
        self._restart = bool(restart)
        self._restart_backoff_s = float(restart_backoff_s)
        self._restart_backoff_cap_s = float(restart_backoff_cap_s)
        self._alpha = float(health_alpha)
        self._tick_s = float(tick_s)
        self.admission = AdmissionController(max_pending=max_pending)

        self._cond = threading.Condition()
        self._pending: deque = deque()   # parked _FleetRequests (redispatch)
        self._inflight_reqs: set = set()  # every unresolved _FleetRequest
        self._replicas: List[_Replica] = []
        # distinguishes a deliberate close() from a monitor crash: the
        # supervisor only restarts the monitor loop when this is False
        self._user_close = False
        self._chaos = chaos
        self._degraded = False  # decode tier dark -> co-located serving
        # fleet-wide aggregates live in the (leaf-locked) registry: the
        # routing path and completion callbacks publish without holding
        # _cond, and a scrape never contends with routing. Per-replica
        # traffic fields stay plain on _Replica, guarded by _cond.
        self.metrics = registry if registry is not None \
            else MetricsRegistry()
        m = self.metrics
        self._m_submitted = m.counter(
            "fleet_submitted_total", "requests offered to the fleet")
        self._m_rejected_submits = m.counter(
            "fleet_rejected_submits_total",
            "submits shed typed before acceptance")
        self._m_completed = m.counter(
            "fleet_completed_total", "requests completed")
        self._m_failed = m.counter(
            "fleet_failed_total", "requests failed on error")
        self._m_expired = m.counter(
            "fleet_expired_total", "requests failed on deadline")
        self._m_redispatched = m.counter(
            "fleet_redispatched_total",
            "attempts re-parked after a replica failure")
        self._m_hedged = m.counter(
            "fleet_hedged_total", "straggler hedge attempts launched")
        self._m_losers_cancelled = m.counter(
            "fleet_losers_cancelled_total",
            "duplicate attempts cancelled after a winner")
        self._m_deaths = m.counter(
            "fleet_deaths_total", "replica deaths observed")
        self._m_restarts = m.counter(
            "fleet_restarts_total", "supervised replica restarts")
        self._m_handoff_resumes = m.counter(
            "fleet_handoff_resumes_total",
            "redispatches resumed from an adopted KV snapshot")
        self._m_handoff_fallbacks = m.counter(
            "fleet_handoff_fallbacks_total",
            "snapshots dropped (invalid/unsupported) for token-0 "
            "regeneration")
        self._m_tier_handoffs = m.counter(
            "fleet_tier_handoffs_total",
            "prefill->decode KVSnapshot handoffs staged by the tier "
            "pipeline")
        self._m_degraded_submits = m.counter(
            "fleet_degraded_submits_total",
            "requests served co-located on the prefill tier while the "
            "decode tier was dark")
        # TTFT vs inter-token latency are *separate* SLOs in a
        # disaggregated topology: prefill capacity bounds the first,
        # decode capacity the second. Keep them in separate histograms.
        self.ttft_hist = m.histogram(
            "fleet_ttft_ms", "time from submit to first token (ms)")
        self.itl_hist = m.histogram(
            "fleet_itl_ms", "mean inter-token latency per request (ms)")
        m.gauge("fleet_degraded_mode",
                "1 while the decode tier has no READY replica and the "
                "fleet serves co-located on the prefill tier",
                fn=lambda: 1.0 if self._degraded else 0.0)
        m.gauge("fleet_replicas", "replica slots in the fleet",
                fn=lambda: len(self._replicas))
        m.gauge("fleet_parked", "requests parked for re-dispatch",
                fn=lambda: len(self._pending))
        m.gauge("fleet_inflight", "unresolved accepted requests",
                fn=lambda: len(self._inflight_reqs))
        m.gauge("fleet_pending", "admission high-watermark occupancy",
                fn=lambda: self.admission.pending)
        m.gauge("fleet_accepted", "requests accepted by fleet admission",
                fn=lambda: self.admission.accepted)
        m.gauge("fleet_rejected", "requests rejected by fleet admission",
                fn=lambda: self.admission.rejected)

        for rid in range(int(replicas)):
            server = factory(rid)  # spawn errors propagate at construction
            if warmup is not None:
                warmup(server)
            self._replicas.append(self._new_replica(rid, 0, server))
        self._tiered = any(r.role != "unified" for r in self._replicas)
        # staged prefill->decode pipeline semantics (KVSnapshot staging,
        # degraded mode, colocated fallback) apply only to the disagg
        # roles; role-pinned tiers ("knn"/"generate" — the RAG pipeline)
        # route by submit(tier=...) and resolve directly
        self._staged = any(r.role in ("prefill", "decode")
                           for r in self._replicas)

        self._runtime = ServingLoop("fleet-monitor",
                                    tick=self._monitor_tick,
                                    wake=self._wake_monitor, chaos=chaos)
        self._runtime.start()
        supervisor().watch(self._runtime, on_death=self._on_monitor_death,
                           restart=True)

    # -- lifecycle state -----------------------------------------------
    @property
    def _closing(self) -> bool:
        """True once the lifecycle left RUNNING (draining or closed)."""
        return self._runtime.state in (LoopState.DRAINING, LoopState.CLOSED)

    @property
    def _stop(self) -> bool:
        return self._runtime.state is LoopState.CLOSED

    # -- construction helpers ------------------------------------------

    def _new_replica(self, rid: int, generation: int,
                     server: Any) -> _Replica:
        if self._breaker_factory is not None:
            breaker = self._breaker_factory()
        else:
            breaker = CircuitBreaker(failure_threshold=0.5, window=16,
                                     min_calls=6, reset_timeout_s=0.25)
        admission = AdmissionController(
            max_pending=self._replica_max_pending)
        srole = getattr(server, "role", None)
        if self._roles is not None:
            role = self._roles[rid]
            if srole is not None and srole != role:
                raise ValueError(
                    f"replica {rid}: fleet roles[{rid}]={role!r} but the "
                    f"factory built a {srole!r} server")
        else:
            role = srole if srole is not None else "unified"
        return _Replica(rid, generation, server, breaker, admission,
                        self._restart_backoff_s, role=role)

    # -- public surface ------------------------------------------------

    def __enter__(self) -> "ReplicaFleet":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    @property
    def replica_count(self) -> int:
        with self._cond:
            return len(self._replicas)

    def submit(self, *args, deadline_s: Optional[float] = None,
               tier: Optional[str] = None, **kwargs) -> Future:
        """Route one request to the healthiest replica. Returns a Future
        that resolves with the replica's result, survives replica death
        via re-dispatch, and fails only with a typed error. Raises
        ``ServerOverloaded`` / ``CircuitOpen`` / ``ReplicaUnavailable``
        synchronously when the fleet cannot accept the request.

        ``tier`` pins the request to replicas of one role (exact match
        — the RAG pipeline routes retrieval to its ``"knn"`` tier and
        generation to its ``"generate"`` tier this way). A pinned
        request never falls back cross-tier: with no READY replica in
        the tier it sheds ``ReplicaUnavailable`` (fresh submit) or
        parks for re-dispatch (accepted work)."""
        if deadline_s is not None and deadline_s <= 0:
            raise ValueError("deadline_s must be positive")
        with self._cond:
            if self._closing:
                raise RuntimeError("ReplicaFleet is closed")
            if tier is not None and not any(r.role == tier
                                            for r in self._replicas):
                raise ValueError(f"no replica fills tier {tier!r}")
        self.admission.acquire()  # fleet-wide high-watermark (429)
        fut = Future()
        fut.add_done_callback(lambda _f: self.admission.release())
        freq = _FleetRequest(
            args, kwargs,
            None if deadline_s is None else Deadline(deadline_s), fut,
            tier=tier)
        with self._cond:
            self._inflight_reqs.add(freq)
        self._m_submitted.inc()
        try:
            routed, reason = self._route_once(freq)
        except ValueError:
            # caller error (bad prompt/shape): fail sync, like the servers
            self._resolve(freq, None, None)  # unlink + release admission
            raise
        if routed:
            return fut
        exc = self._unrouted_error(freq, reason)
        self._resolve(freq, None, exc, rejected=True)
        raise exc

    def _unrouted_error(self, freq: _FleetRequest,
                        reason: str) -> Exception:
        """The typed submit-time shed for a request no replica took."""
        if reason == "breaker":
            return CircuitOpen(
                "every healthy replica's circuit breaker is open")
        if reason == "rejected" and isinstance(freq.last_error,
                                               ResilienceError):
            return freq.last_error
        return ReplicaUnavailable(
            "no replica can accept the request (all dead, draining, "
            "or restarting)")

    def adopt(self, snapshot: KVSnapshot, *,
              deadline_s: Optional[float] = None) -> Future:
        """Accept a harvested ``KVSnapshot`` as a brand-new fleet
        request: the next dispatch resumes it at position N on the
        healthiest (decode-capable) replica via ``adopt_request``, with
        the token-0 fallback replaying the original call reconstructed
        from the snapshot header — bit-exact either way (the fold_in
        key schedule makes regeneration exact), the snapshot only saves
        the recompute. The deadline follows the handoff precedence: an
        explicit ``deadline_s`` wins, else the snapshot's
        ``deadline_remaining`` duration re-arms here (monotonic-deadline
        rule — it survives wall-clock skew between hosts), else no
        deadline. This is the entry the cross-host federation uses to
        re-home a dead host's in-flight requests on a surviving fleet."""
        if deadline_s is not None and deadline_s <= 0:
            raise ValueError("deadline_s must be positive")
        if deadline_s is None and snapshot.deadline_remaining is not None:
            # expired-in-flight budgets still dispatch once: the typed
            # DeadlineExceeded must come from the routing path, not a
            # constructor ValueError the wire protocol can't express
            deadline_s = max(0.001, snapshot.deadline_remaining)
        args = (snapshot.prompt, snapshot.max_tokens)
        kwargs = {"temperature": snapshot.temperature,
                  "top_k": snapshot.top_k, "seed": snapshot.seed,
                  "eos_id": snapshot.eos_id}
        with self._cond:
            if self._closing:
                raise RuntimeError("ReplicaFleet is closed")
        self.admission.acquire()  # fleet-wide high-watermark (429)
        fut = Future()
        fut.add_done_callback(lambda _f: self.admission.release())
        freq = _FleetRequest(
            args, kwargs,
            None if deadline_s is None else Deadline(deadline_s), fut)
        freq.snapshot = snapshot
        with self._cond:
            self._inflight_reqs.add(freq)
        self._m_submitted.inc()
        try:
            routed, reason = self._route_once(freq)
        except ValueError:
            self._resolve(freq, None, None)  # unlink + release admission
            raise
        if routed:
            return fut
        exc = self._unrouted_error(freq, reason)
        self._resolve(freq, None, exc, rejected=True)
        raise exc

    def kill_replica(self, rid: int) -> bool:
        """Abruptly kill one replica (ops drill / chaos hook): its server
        is closed with a zero drain budget, every request it held fails
        typed and re-dispatches to the survivors, and the monitor respawns
        it after the restart backoff."""
        with self._cond:
            rep = self._replicas[rid]
            if rep.state in (DEAD, RETIRED):
                return False
            rep.state = DEAD
            rep.restart_at = time.monotonic() + rep.backoff_s
            server = rep.server
            self._cond.notify_all()
        self._m_deaths.inc()
        try:
            server.close(timeout=0.0)
        except Exception:
            pass
        return True

    def retire_replica(self, rid: int,
                       timeout: Optional[float] = 30.0, *,
                       migrate: bool = False) -> bool:
        """Gracefully drain one replica and take it out of the fleet for
        good (scale-down). Returns False if it was not READY.

        ``migrate=True`` moves live requests off the replica instead of
        waiting them out: the server snapshots each in-flight request
        and fails it ``RequestMigrated`` with the snapshot attached, and
        the monitor resumes every one on a surviving replica at its
        exact stream position (servers without a migrate-capable
        ``drain`` fall back to the plain wait-out drain)."""
        with self._cond:
            rep = self._replicas[rid]
            if rep.state != READY:
                return False
            rep.state = DRAINING
            server = rep.server
        try:
            if migrate:
                try:
                    server.drain(timeout, migrate=True)
                except TypeError:  # server predates drain(migrate=...)
                    server.drain(timeout)
            else:
                server.drain(timeout)
            server.close(timeout=5.0)
        except Exception:
            pass
        with self._cond:
            if self._replicas[rid] is rep:
                rep.state = RETIRED
            self._cond.notify_all()
        return True

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Block until every accepted request has resolved (or the
        timeout passes). New submits are still accepted while draining —
        pair with ``close()`` for shutdown."""
        dl = None if timeout is None else Deadline(timeout)
        with self._cond:
            while self._inflight_reqs or self._pending:
                if dl is not None and dl.expired():
                    return False
                wait_s = self._tick_s * 10.0
                if dl is not None:
                    rem = dl.remaining()
                    if rem < wait_s:
                        wait_s = rem if rem > 0.001 else 0.001
                self._cond.wait(timeout=wait_s)
        return True

    def close(self, timeout: float = 30.0) -> None:
        """Stop accepting work, give in-flight requests ``timeout``
        seconds to finish (re-dispatch keeps running), then stop the
        monitor, close every replica, and fail any stragglers typed.
        Idempotent."""
        already = self._stop
        with self._cond:
            # before the drain begins, so a chaos kill landing mid-drain
            # cannot win a restart race against this deliberate close
            self._user_close = True
        self._runtime.begin_drain()   # submit() now rejects typed
        if not already:
            self.drain(timeout)
        self._runtime.close(5.0)
        with self._cond:
            reps = list(self._replicas)
        for rep in reps:
            try:
                rep.server.close(timeout=1.0)
            except Exception:
                pass
        with self._cond:
            for rep in reps:
                if rep.state not in (RETIRED,):
                    rep.state = RETIRED
            leftovers = list(self._inflight_reqs)
            leftovers.extend(self._pending)
            self._pending.clear()
        closed_exc = RuntimeError(
            "ReplicaFleet closed with the request still in flight")
        for freq in leftovers:
            self._resolve(freq, None, closed_exc)

    def stats(self) -> dict:
        with self._cond:
            reps = list(self._replicas)
            parked = len(self._pending)
            inflight = len(self._inflight_reqs)
            degraded = self._degraded
            per = []
            for r in reps:
                per.append({
                    "rid": r.rid,
                    "state": r.state,
                    "role": r.role,
                    "generation": r.generation,
                    "health_score": _score(r),
                    "ewma_latency_ms": r.ewma_ms,
                    "failure_ewma": r.fail_ewma,
                    "inflight": r.inflight,
                    "restarts": r.restarts,
                    "spawn_failures": r.spawn_failures,
                    "dispatched": r.dispatched,
                    "completed": r.completed,
                    "failed": r.failed,
                    "rejected": r.rejected,
                })
        # aggregate counters come off the registry — assembled OUTSIDE
        # _cond — and the legacy key set/order is preserved byte-for-byte
        out = {
            "replica_count": len(reps),
            "submitted": int(self._m_submitted.value),
            "rejected_submits": int(self._m_rejected_submits.value),
            "completed": int(self._m_completed.value),
            "failed": int(self._m_failed.value),
            "expired": int(self._m_expired.value),
            "redispatched": int(self._m_redispatched.value),
            "hedged": int(self._m_hedged.value),
            "losers_cancelled": int(self._m_losers_cancelled.value),
            "deaths": int(self._m_deaths.value),
            "restarts": int(self._m_restarts.value),
            "parked": parked,
            "inflight": inflight,
            "handoff_resumes": int(self._m_handoff_resumes.value),
            "handoff_fallbacks": int(self._m_handoff_fallbacks.value),
        }
        # server/breaker/admission calls take their own locks: keep them
        # outside _cond (replica callbacks already hold server locks when
        # they take _cond, so the reverse order would be a lock cycle)
        for blk, r in zip(per, reps):
            blk["breaker"] = r.breaker.state
            blk["breaker_trips"] = r.prior_trips + r.breaker.open_count
            blk["admission"] = {"pending": r.admission.pending,
                                "accepted": r.admission.accepted,
                                "rejected": r.admission.rejected}
            try:
                blk["server"] = r.server.stats()
            except Exception:
                blk["server"] = None
        out["admission"] = {"pending": self.admission.pending,
                            "accepted": self.admission.accepted,
                            "rejected": self.admission.rejected,
                            "max_pending": self.admission.max_pending}
        out["replicas"] = per
        # disaggregation keys append AFTER the legacy set
        out["tier_handoffs"] = int(self._m_tier_handoffs.value)
        out["degraded_submits"] = int(self._m_degraded_submits.value)
        out["degraded_mode"] = degraded
        if self._tiered:
            tiers: Dict[str, dict] = {}
            for blk, r in zip(per, reps):
                t = tiers.setdefault(r.role, {
                    "replicas": 0, "ready": 0, "inflight": 0,
                    "dispatched": 0, "completed": 0, "failed": 0})
                t["replicas"] += 1
                t["ready"] += 1 if blk["state"] == READY else 0
                t["inflight"] += blk["inflight"]
                t["dispatched"] += blk["dispatched"]
                t["completed"] += blk["completed"]
                t["failed"] += blk["failed"]
            out["tiers"] = tiers
        return out

    # -- per-tier levers (autoscaler surface) --------------------------

    def tier_replicas(self, role: str) -> List[Any]:
        """READY servers currently filling ``role`` (exact match)."""
        with self._cond:
            return [r.server for r in self._replicas
                    if r.role == role and r.state == READY]

    def tier_stats(self, role: str) -> dict:
        """Aggregate queue/outcome counters over one tier's READY
        replica servers — the observation surface for a per-tier
        ``FleetTierTarget`` autoscaler lever. Server stats() calls take
        server locks, so this never holds ``_cond`` across them."""
        out = {"replicas": 0, "queued": 0, "expired": 0, "completed": 0,
               "active_slots": 0, "slots": 0}
        for server in self.tier_replicas(role):
            try:
                st = server.stats()
            except Exception:
                continue
            out["replicas"] += 1
            for k in ("queued", "expired", "completed", "slots"):
                out[k] += st.get(k, 0)
            out["active_slots"] += getattr(server, "active_slot_cap", 0)
        return out

    def set_tier_active_slots(self, role: str, n: int) -> int:
        """Set the active-slot admission cap on every READY replica of
        one tier (the per-tier scaling lever: prefill and decode
        capacity move independently). Returns the applied per-replica
        cap, or 0 when the tier has no capable READY replica."""
        applied = 0
        for server in self.tier_replicas(role):
            if hasattr(server, "set_active_slots"):
                applied = server.set_active_slots(n)
        return applied

    # -- routing core (hot path) ---------------------------------------

    def _tier_route(self, freq: _FleetRequest,
                    skip: set) -> Tuple[List[_Replica], bool]:
        """Tier-aware candidate filter (called under ``_cond``; on the
        graftcheck hot list — no host-sync coercions here). Stage 1 (no
        snapshot staged) prefers prefill-capable replicas; stage 2
        (snapshot in hand) prefers decode-capable ones. When the decode
        tier has no READY replica anywhere, flips degraded mode and
        returns ``colocate=True`` for fresh requests so the dispatch
        pins ``export_kv=False`` — co-located serving on the prefill
        tier instead of exporting snapshots nobody can adopt. A dark
        *preferred* tier otherwise degrades to any READY replica."""
        ready = [r for r in self._replicas
                 if r.state == READY and r.rid not in skip
                 and r.rid not in freq.active]
        if freq.tier is not None:
            # role-pinned request (RAG tier route): exact-role match
            # only, no cross-tier fallback — a knn query cannot run on
            # a generation replica
            return [r for r in ready if r.role == freq.tier], False
        if not self._staged:
            return ready, False
        stage2 = freq.snapshot is not None
        want = ("decode", "unified") if stage2 else ("prefill", "unified")
        cands = [r for r in ready if r.role in want]
        # tier darkness is fleet-wide readiness, not the skip-filtered
        # view: a replica we merely already tried must not fake a dark
        # tier
        decode_dark = not any(
            r.state == READY and r.role in ("decode", "unified")
            for r in self._replicas)
        if decode_dark:
            self._note_degraded(True)
        if not cands:
            cands = ready  # preferred tier dark: cross-tier fallback
        return cands, decode_dark and not stage2

    def _note_degraded(self, dark: bool) -> None:
        """Flip the degraded-mode flag (``_cond`` held). The typed
        transition log fires once per flip, not once per request."""
        if dark == self._degraded:
            return
        self._degraded = dark
        if dark:
            log.warning(
                "fleet degraded mode ENTERED: decode tier has no READY "
                "replica; serving co-located on the prefill tier")
        else:
            log.warning(
                "fleet degraded mode cleared: decode tier healthy again")

    def _route_once(self, freq: _FleetRequest,
                    hedge: bool = False) -> Tuple[bool, str]:
        """Try to dispatch ``freq`` to the best replica right now.
        Returns ``(True, "dispatched")`` when an attempt is in flight (or
        the request resolved), else ``(False, reason)`` with reason one of
        ``"noreplica"`` (nothing READY), ``"breaker"`` (READY but every
        breaker open), ``"rejected"`` (every candidate refused at
        admission/submit). ValueError from the server (caller error)
        propagates. Never called with ``_cond`` held."""
        skip: set = set()
        saw_breaker_block = False
        saw_rejection = False
        while True:
            with self._cond:
                if freq.resolved or freq.future.cancelled():
                    return True, "dispatched"
                if freq.deadline is not None and freq.deadline.expired():
                    expired = True
                else:
                    expired = False
                rep = None
                colocate = False
                if not expired:
                    cands, colocate = self._tier_route(freq, skip)
                    if cands:
                        fresh = [r for r in cands
                                 if r.rid not in freq.tried]
                        pool = fresh if fresh else cands
                        best = min(pool, key=_score)
                        if best.breaker.allow():
                            best.inflight += 1
                            best.dispatched += 1
                            rep = best
                        else:
                            saw_breaker_block = True
                            skip.add(best.rid)
                            continue
                rem = None
                if rep is not None and freq.deadline is not None:
                    rem = freq.deadline.remaining()
                    if rem < 0.001:
                        rem = 0.001
            if expired:
                self._resolve(freq, None, DeadlineExceeded(
                    "request deadline expired before dispatch"))
                return True, "dispatched"
            if rep is None:
                if saw_rejection:
                    return False, "rejected"
                if saw_breaker_block:
                    return False, "breaker"
                return False, "noreplica"
            # outside _cond from here: per-replica admission + dispatch
            try:
                rep.admission.acquire()
            except ServerOverloaded as e:
                with self._cond:
                    rep.inflight -= 1
                    rep.rejected += 1
                    freq.last_error = e
                saw_rejection = True
                skip.add(rep.rid)
                continue
            t0 = time.monotonic()
            with self._cond:
                snap = freq.snapshot
            # degraded-mode dispatch onto the prefill tier: fresh
            # requests are pinned export_kv=False (serve co-located,
            # don't export snapshots nobody can adopt) and staged
            # snapshots adopt in place (adoption always decodes to
            # completion)
            colocated = (self._staged and rep.role == "prefill"
                         and (colocate or snap is not None))
            inner = None
            if snap is not None and hasattr(rep.server, "adopt_request"):
                # crash-durable failover: resume from the newest
                # harvested KV snapshot instead of regenerating from
                # token 0 — bit-exact either way, the snapshot only
                # saves the recompute
                try:
                    if freq.deadline is not None:
                        inner = rep.server.adopt_request(
                            snap, deadline_s=rem)
                    else:
                        inner = rep.server.adopt_request(snap)
                except SnapshotError:
                    # corrupted/unsupported snapshot is never fatal:
                    # drop it and fall through to a token-0 submit on
                    # this same replica
                    with self._cond:
                        if freq.snapshot is snap:
                            freq.snapshot = None
                    self._m_handoff_fallbacks.inc()
                except Exception as e:
                    # adoption refused (overloaded, breaker, closing):
                    # same handling as a submit rejection — try the
                    # next replica, snapshot kept for the next attempt
                    rep.admission.release()
                    with self._cond:
                        rep.inflight -= 1
                        rep.rejected += 1
                        rep.fail_ewma = ((1.0 - self._alpha)
                                         * rep.fail_ewma + self._alpha)
                        freq.last_error = e
                    rep.breaker.record_failure()
                    saw_rejection = True
                    skip.add(rep.rid)
                    continue
                else:
                    self._m_handoff_resumes.inc()
            if inner is None:
                try:
                    kwargs = freq.kwargs
                    if freq.deadline is not None or colocated:
                        kwargs = dict(kwargs)
                        if freq.deadline is not None:
                            kwargs["deadline_s"] = rem
                        if colocated:
                            kwargs["export_kv"] = False
                    inner = rep.server.submit(*freq.args, **kwargs)
                except ValueError:
                    rep.admission.release()
                    with self._cond:
                        rep.inflight -= 1
                    raise
                except Exception as e:
                    rep.admission.release()
                    with self._cond:
                        rep.inflight -= 1
                        rep.rejected += 1
                        rep.fail_ewma = ((1.0 - self._alpha)
                                         * rep.fail_ewma + self._alpha)
                        freq.last_error = e
                    rep.breaker.record_failure()
                    saw_rejection = True
                    skip.add(rep.rid)
                    continue
            with self._cond:
                freq.tried.add(rep.rid)
                freq.attempts += 1
                freq.active[rep.rid] = inner
                freq.t_dispatch = t0
                if hedge:
                    freq.hedges += 1
            if hedge:
                self._m_hedged.inc()
            if colocated:
                self._m_degraded_submits.inc()
            # if `inner` is already done this fires the callback inline
            inner.add_done_callback(
                functools.partial(self._replica_done, freq, rep, t0))
            return True, "dispatched"

    def _replica_done(self, freq: _FleetRequest, rep: _Replica, t0: float,
                      fut: Future) -> None:
        """Completion arbiter for one replica attempt. May run inline on
        the replica server's own threads *while that server holds its
        internal lock* — so this takes only ``_cond`` and never calls
        back into any replica server."""
        lat_ms = (time.monotonic() - t0) * 1000.0
        cancelled = fut.cancelled()
        exc = None if cancelled else fut.exception()
        died = isinstance(exc, ReplicaKilled)
        with self._cond:
            current = self._replicas[rep.rid] is rep
            rep.inflight -= 1
            if cancelled:
                pass
            elif exc is None:
                rep.completed += 1
                if rep.ewma_ms == 0.0:
                    rep.ewma_ms = lat_ms
                else:
                    rep.ewma_ms = ((1.0 - self._alpha) * rep.ewma_ms
                                   + self._alpha * lat_ms)
                rep.fail_ewma = (1.0 - self._alpha) * rep.fail_ewma
            else:
                rep.failed += 1
                rep.fail_ewma = ((1.0 - self._alpha) * rep.fail_ewma
                                 + self._alpha)
            counted_death = died and current and rep.state == READY
            if counted_death:
                rep.state = DEAD
                rep.restart_at = time.monotonic() + rep.backoff_s
            # harvest the attempt's KV snapshot off the failed future
            # (periodic snapshotting / drain-migrate attach it there);
            # newest stream position wins across attempts, so failover
            # resumes from the furthest crash-durable point
            if exc is not None and not cancelled:
                snap = getattr(fut, "_kv_snapshot", None)
                if snap is not None and (
                        freq.snapshot is None
                        or snap.count > freq.snapshot.count):
                    freq.snapshot = snap
            freq.active.pop(rep.rid, None)
            has_twin = len(freq.active) > 0
            is_resolved = freq.resolved
            stopping = self._stop
            self._cond.notify_all()
        if counted_death:
            self._m_deaths.inc()
        rep.admission.release()
        if cancelled:
            return
        if exc is None:
            rep.breaker.record_success()
            result = fut.result()
            if self._staged and isinstance(result, KVSnapshot):
                # stage 1 of the tier pipeline complete: the prefill
                # replica exported the request as a snapshot — stage it
                # for the decode tier instead of resolving the caller
                self._stage_handoff(freq, fut, result)
                return
            self._note_first_token(freq, fut)
            with self._cond:
                tfirst = freq.t_first
            if tfirst and hasattr(result, "__len__") and len(result) > 1:
                self.itl_hist.observe((time.monotonic() - tfirst)
                                      * 1000.0 / (len(result) - 1))
            self._resolve(freq, result, None)
            return
        rep.breaker.record_failure()
        if is_resolved:
            return
        if isinstance(exc, DeadlineExceeded):
            # the budget is global: a hedge twin cannot beat it either
            self._resolve(freq, None, exc)
            return
        if has_twin:
            return  # the hedge twin is still running and may win
        if stopping:
            self._resolve(freq, None, exc)
            return
        if freq.deadline is not None and freq.deadline.expired():
            self._resolve(freq, None, DeadlineExceeded(
                "request deadline expired during replica failover"))
            return
        if (self._max_redispatch is not None
                and freq.attempts > self._max_redispatch):
            self._resolve(freq, None, exc)
            return
        with self._cond:
            parked = not freq.resolved and not self._stop
            if parked:
                self._pending.append(freq)
                self._cond.notify_all()
        if parked:
            self._m_redispatched.inc()
            return
        self._resolve(freq, None, exc)

    def _note_first_token(self, freq: _FleetRequest, fut: Future) -> None:
        """Record TTFT once per request, the first time the fleet learns
        a token exists — off the replica's ``_t_first`` monotonic stamp
        (snapshot handoff or final completion). Futures without a stamp
        (adoption resumes, inference servers) never observe: their first
        token predates this attempt or doesn't exist."""
        tf = getattr(fut, "_t_first", None)
        if tf is None:
            return
        with self._cond:
            if freq.t_first:
                return
            freq.t_first = tf
            t_submit = freq.t_submit
        self.ttft_hist.observe((tf - t_submit) * 1000.0)

    def _stage_handoff(self, freq: _FleetRequest, fut: Future,
                       snap: KVSnapshot) -> None:
        """Stage 2 of the tier pipeline: a prefill attempt resolved to a
        KVSnapshot instead of tokens. Record TTFT (the first token is in
        the snapshot), stash the snapshot, and park the request for the
        monitor to route onto the decode tier — behind the same caller
        Future, which only ever resolves with final tokens. May run
        inline under the prefill server's lock: takes only ``_cond``."""
        self._note_first_token(freq, fut)
        with self._cond:
            parked = not freq.resolved and not self._stop
            if parked:
                if (freq.snapshot is None
                        or snap.count > freq.snapshot.count):
                    freq.snapshot = snap
                if freq not in self._pending:  # hedge twin staged first
                    self._pending.append(freq)
                self._cond.notify_all()
        if parked:
            self._m_tier_handoffs.inc()
            return
        if not freq.resolved:
            self._resolve(freq, None, RuntimeError(
                "ReplicaFleet stopped with the request mid-handoff"))

    def _resolve(self, freq: _FleetRequest, value: Any,
                 exc: Optional[BaseException], *,
                 rejected: bool = False) -> bool:
        """Resolve the caller-facing future exactly once (first caller
        wins) and cancel any still-running duplicate attempts. Submit-time
        rejections (``rejected=True``: typed shed re-raised to the caller,
        or ``exc is None and value is None`` for ValueError unlinks) count
        as ``rejected_submits`` rather than ``failed`` — the request was
        never accepted, so ``submitted == completed + failed + expired +
        rejected_submits`` once the fleet is idle."""
        with self._cond:
            if freq.resolved:
                return False
            freq.resolved = True
            self._inflight_reqs.discard(freq)
            losers = list(freq.active.values())
            self._cond.notify_all()
        if losers:
            self._m_losers_cancelled.inc(len(losers))
        if rejected or (exc is None and value is None):
            self._m_rejected_submits.inc()
        elif exc is None:
            self._m_completed.inc()
        elif isinstance(exc, DeadlineExceeded):
            self._m_expired.inc()
        else:
            self._m_failed.inc()
        for loser in losers:
            loser.cancel()  # queued attempts die; running ones are ignored
        try:
            if exc is None:
                if freq.t_first:
                    # TTFT stamp rides the caller future (same contract
                    # as the replica servers'), so a pipeline stacked on
                    # the fleet — RAG — can observe end-to-end TTFT
                    freq.future._t_first = freq.t_first
                freq.future.set_result(value)
            else:
                # the newest harvested snapshot rides the failed future
                # (same contract as GenerationServer's): whoever holds
                # it — the federation host publisher — can re-home the
                # request at its final crash-durable position
                snap = freq.snapshot
                if snap is not None:
                    cur = getattr(freq.future, "_kv_snapshot", None)
                    if cur is None or snap.count > cur.count:
                        freq.future._kv_snapshot = snap
                freq.future.set_exception(exc)
        except Exception:
            pass  # caller cancelled the fleet future: outcome dropped
        return True

    # -- monitor: redispatch, hedging, supervised restart --------------

    def _wake_monitor(self) -> None:
        """Runtime wake hook: nudge a tick blocked on ``_cond``."""
        with self._cond:
            self._cond.notify_all()

    def _monitor_tick(self) -> bool:
        """One monitor round, hosted by the ``ServingLoop`` tick thread
        ("fleet-monitor"). Returns False only on a clean stop."""
        with self._cond:
            if self._stop:
                return False
            self._cond.wait(timeout=self._tick_s)
            if self._stop:
                return False
            now = time.monotonic()
            work = []
            while self._pending:
                work.append(self._pending.popleft())
            spawn = []
            if self._restart:
                for r in self._replicas:
                    if r.state == DEAD and r.restart_at <= now:
                        r.state = SPAWNING
                        spawn.append(r.rid)
            if self._staged and self._degraded and any(
                    r.state == READY
                    and r.role in ("decode", "unified")
                    for r in self._replicas):
                # a decode-capable replica healed: leave degraded
                # mode; new work flows through the tier pipeline
                self._note_degraded(False)
            hedges = []
            if self._hedge_after_s is not None:
                for freq in self._inflight_reqs:
                    if (not freq.resolved
                            and len(freq.active) == 1
                            and freq.hedges < self._max_hedges
                            and now - freq.t_dispatch
                            >= self._hedge_after_s):
                        hedges.append(freq)
            # crash-durable publication: mirror each live attempt's
            # newest periodic snapshot (generation servers attach them
            # to the inner future as they decode) onto the fleet
            # request and the caller-facing future, so a host-level
            # wrapper (federation.FleetHost) can ship the newest stream
            # position off-process without reaching into replica
            # internals
            for freq in self._inflight_reqs:
                for inner in freq.active.values():
                    snap = getattr(inner, "_kv_snapshot", None)
                    if snap is not None and (
                            freq.snapshot is None
                            or snap.count > freq.snapshot.count):
                        freq.snapshot = snap
                if freq.snapshot is not None:
                    cur = getattr(freq.future, "_kv_snapshot", None)
                    if cur is None or freq.snapshot.count > cur.count:
                        freq.future._kv_snapshot = freq.snapshot
        for rid in spawn:
            self._respawn(rid)
        for freq in work:
            self._service_parked(freq)
        for freq in hedges:
            try:
                self._route_once(freq, hedge=True)
            except ValueError:
                pass  # original attempt is still running; let it win
        return True

    def _on_monitor_death(self, loop, exc) -> bool:
        """Supervisor recovery hook: the monitor thread died. Parked and
        in-flight requests are untouched when the monitor restarts (the
        replicas keep serving; redispatch resumes on the fresh thread) —
        but on a deliberately closing fleet the parked queue is failed
        typed so nothing hangs."""
        with self._cond:
            again = not self._user_close
            parked = [] if again else list(self._pending)
            if not again:
                self._pending.clear()
            self._cond.notify_all()
        err = LoopCrashed(f"fleet-monitor died with the request parked: "
                          f"{exc!r}")
        for freq in parked:
            self._resolve(freq, None, err)
        return again

    def _service_parked(self, freq: _FleetRequest) -> None:
        try:
            routed, _reason = self._route_once(freq)
        except ValueError as e:
            self._resolve(freq, None, e)
            return
        if routed:
            return
        with self._cond:
            if not freq.resolved and not self._stop:
                self._pending.append(freq)  # retry next tick
                return
        self._resolve(freq, None, RuntimeError(
            "ReplicaFleet stopped with the request still queued"))

    def _respawn(self, rid: int) -> None:
        """Supervised restart of a dead replica (monitor thread only):
        close the corpse, rebuild via the factory, warm it, and swap it in
        with a fresh breaker. Spawn failures back off exponentially."""
        with self._cond:
            rep = self._replicas[rid]
            old_server = rep.server
        try:
            old_server.close(timeout=0.0)
        except Exception:
            pass
        try:
            server = self._factory(rid)
            if self._warmup is not None:
                with self._cond:
                    rep.state = WARMING
                self._warmup(server)
        except Exception:
            with self._cond:
                rep.state = DEAD
                rep.spawn_failures += 1
                rep.backoff_s = min(rep.backoff_s * 2.0,
                                    self._restart_backoff_cap_s)
                rep.restart_at = time.monotonic() + rep.backoff_s
            return
        fresh = self._new_replica(rid, 0, server)
        with self._cond:
            old = self._replicas[rid]
            fresh.generation = old.generation + 1
            fresh.restarts = old.restarts + 1
            fresh.spawn_failures = old.spawn_failures
            fresh.prior_trips = old.prior_trips + old.breaker.open_count
            # traffic counters are cumulative per replica *slot*: a restart
            # replaces the server, not the slot's ops history
            fresh.dispatched = old.dispatched
            fresh.completed = old.completed
            fresh.failed = old.failed
            fresh.rejected = old.rejected
            self._replicas[rid] = fresh
            self._cond.notify_all()
        self._m_restarts.inc()
