"""Multi-host distributed runtime.

Replaces the reference's cluster transports — Spark broadcast/treeAggregate
(ParameterAveragingTrainingMaster.java:367-490,867) and the Aeron UDP parameter
server (ParameterServerTrainerContext.java:43, ParameterServerTrainer.java:48,68)
— with the JAX distributed runtime: one `jax.distributed.initialize` per host,
then every mesh in this package spans all hosts' devices and the SAME sharded
step runs SPMD; XLA routes intra-pod reductions over ICI and cross-pod
reductions over DCN. There is no separate parameter-server process: the
"server" is the collective.

Synchronous parity: Spark parameter averaging == ParallelWrapper AVERAGING mode
on a global mesh (treeAggregate's sum/divide IS pmean). Async parameter-server
semantics (Aeron push/pull) are intentionally not reproduced — on TPU meshes
synchronous collectives are strictly faster than host-mediated async exchange;
the `GradientsAccumulator` threshold-compression path (EncodingHandler.java:65)
is provided for DCN-limited topologies in optimize/accumulation.py.
"""

from __future__ import annotations

import jax


def initialize(coordinator_address=None, num_processes=None, process_id=None,
               **kwargs) -> None:
    """Join the multi-host runtime (call once per host before any mesh work).

    With no arguments, defers to jax.distributed.initialize's environment
    auto-detection (the standard call on TPU pod slices). Explicitly passing
    ``num_processes=1`` is the single-process no-op. Mirrors the role of Spark
    context + Aeron MediaDriver bootstrap in the reference, in one call.
    """
    if num_processes == 1:
        return
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id, **kwargs)


def global_device_count() -> int:
    return jax.device_count()


def local_device_count() -> int:
    return jax.local_device_count()


def process_index() -> int:
    return jax.process_index()


def is_coordinator() -> bool:
    return jax.process_index() == 0
