"""Sequence/context parallelism: ring attention over a mesh axis.

No reference equivalent (SURVEY §5: the reference's only long-sequence tool
is truncated BPTT) — this is the TPU-native capability the task requires for
long contexts: shard the TIME axis of attention across devices and rotate
key/value blocks around the ring with ``lax.ppermute`` while accumulating a
streaming (flash-attention-style) softmax — peak memory per device drops from
O(T^2) to O(T * T/n), and the block rotations ride the ICI ring concurrently
with the blockwise matmuls (Liu et al. 2023, Ring Attention).

All ops are differentiable (scan + ppermute), so the same code path serves
training; gradients flow around the ring in reverse automatically.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from deeplearning4j_tpu.parallel.mesh import shard_map_compat

SEQ_AXIS = "seq"
_MIN_LOGIT = -1e4  # running-max clamp: keeps exp() well-defined for
_MASKED = -1e30    # fully-masked blocks (see _block_update)


def _block_update(q_blk, k_cur, v_cur, m, l, acc, q_off, k_off, causal):
    """One blockwise softmax accumulation step (online softmax)."""
    d = q_blk.shape[-1]
    logits = jnp.einsum("bhqd,bhkd->bhqk", q_blk, k_cur) / jnp.sqrt(
        jnp.asarray(d, q_blk.dtype))
    if causal:
        Tq, Tk = logits.shape[-2], logits.shape[-1]
        qpos = q_off + jnp.arange(Tq)
        kpos = k_off + jnp.arange(Tk)
        keep = qpos[:, None] >= kpos[None, :]
        logits = jnp.where(keep, logits, _MASKED)
    row_max = jnp.max(logits, axis=-1)                       # [B,H,Tq]
    new_m = jnp.maximum(jnp.maximum(m, row_max), _MIN_LOGIT)
    p = jnp.exp(logits - new_m[..., None])                   # [B,H,Tq,Tk]
    scale = jnp.exp(m - new_m)                               # [B,H,Tq]
    l = l * scale + jnp.sum(p, axis=-1)
    acc = acc * scale[..., None] + jnp.einsum("bhqk,bhkd->bhqd", p, v_cur)
    return new_m, l, acc


def ring_attention(q, k, v, *, mesh: Mesh, axis: str = SEQ_AXIS,
                   causal: bool = False):
    """Exact attention with the time axis sharded over ``axis``.

    q/k/v: [B, H, T, d] global arrays (T divisible by the axis size).
    Returns [B, H, T, d], numerically equal to single-device
    softmax(qk^T/sqrt(d))v up to float tolerance.
    """
    n = mesh.shape[axis]

    def shard_fn(q_blk, k_blk, v_blk):
        i = lax.axis_index(axis)
        Tl = q_blk.shape[2]
        q_off = i * Tl
        m0 = jnp.full(q_blk.shape[:3], _MIN_LOGIT, q_blk.dtype)
        l0 = jnp.zeros(q_blk.shape[:3], q_blk.dtype)
        acc0 = jnp.zeros_like(q_blk)
        perm = [(s, (s + 1) % n) for s in range(n)]

        def body(carry, step):
            k_cur, v_cur, m, l, acc = carry
            # after `step` rotations, this device holds block (i - step) % n
            blk = (i - step) % n
            m, l, acc = _block_update(q_blk, k_cur, v_cur, m, l, acc,
                                      q_off, blk * Tl, causal)
            k_nxt = lax.ppermute(k_cur, axis, perm)
            v_nxt = lax.ppermute(v_cur, axis, perm)
            return (k_nxt, v_nxt, m, l, acc), 0

        (_, _, _, l, acc), _ = lax.scan(
            body, (k_blk, v_blk, m0, l0, acc0), jnp.arange(n))
        return acc / jnp.maximum(l, 1e-12)[..., None]

    spec = P(None, None, axis, None)
    fn = shard_map_compat(shard_fn, mesh=mesh, in_specs=(spec, spec, spec),
                          out_specs=spec, check=False)
    return fn(q, k, v)


def ulysses_attention(q, k, v, *, mesh: Mesh, axis: str = SEQ_AXIS,
                      causal: bool = False):
    """All-to-all (DeepSpeed-Ulysses style) sequence parallelism.

    Where ring attention keeps the TIME axis sharded and rotates K/V blocks
    ``n`` times around the ring, this re-shards with two collectives: an
    ``all_to_all`` turns the layout from sequence-sharded [B, H, T/n, d]
    into HEAD-sharded [B, H/n, T, d], each device runs ordinary
    full-sequence attention for its own heads, and a second ``all_to_all``
    restores sequence sharding. Communication is 2 all-to-alls per tensor
    (vs n ppermute rounds) — the better trade when heads >= devices and the
    per-device time block is small; ring wins when T is huge and H is
    small (Jacobs et al. 2023, DeepSpeed-Ulysses). Requires H divisible by
    the axis size. Differentiable (all_to_all has a transpose rule), so
    training works through it unchanged.

    q/k/v: [B, H, T, d] global arrays; returns [B, H, T, d], numerically
    equal to single-device softmax(qk^T/sqrt(d))v up to float tolerance.
    """
    n = mesh.shape[axis]
    H = q.shape[1]
    if H % n != 0:
        raise ValueError(
            f"ulysses_attention needs heads ({H}) divisible by the "
            f"'{axis}' axis size ({n}); use ring_attention otherwise")

    def shard_fn(q_blk, k_blk, v_blk):
        # seq-sharded -> head-sharded: split heads, concat time blocks
        # (device order == time order, so concatenation restores the
        # global sequence)
        def to_heads(x):
            return lax.all_to_all(x, axis, split_axis=1, concat_axis=2,
                                  tiled=True)

        from deeplearning4j_tpu.nn.conf.layers.attention import (
            scaled_dot_attention,
        )

        ql, kl, vl = to_heads(q_blk), to_heads(k_blk), to_heads(v_blk)
        o = scaled_dot_attention(ql, kl, vl, causal=causal)
        # head-sharded -> seq-sharded
        return lax.all_to_all(o, axis, split_axis=2, concat_axis=1,
                              tiled=True)

    spec = P(None, None, axis, None)
    fn = shard_map_compat(shard_fn, mesh=mesh, in_specs=(spec, spec, spec),
                          out_specs=spec, check=False)
    return fn(q, k, v)


def sequence_parallel_self_attention(layer, params, x, *, mesh: Mesh,
                                     axis: str = SEQ_AXIS,
                                     causal=None, impl: str = "ring"):
    """Run a SelfAttentionLayer forward with the sequence axis sharded:
    pointwise projections stay local to each time shard; the attention core
    is the ring (``impl='ring'``) or two all-to-alls (``impl='ulysses'``,
    needs heads divisible by the axis size). Inference-mode equal to
    ``layer.forward`` (incl. the output activation; no mask support — pad
    to multiples of the axis size instead, standard for long-context)."""
    causal = layer.causal if causal is None else causal
    H = layer.n_heads

    def project(W):
        y = jnp.einsum("btf,fo->bto", x, W)
        B, T, O = y.shape
        return y.reshape(B, T, H, O // H).transpose(0, 2, 1, 3)

    q, k, v = (project(params["Wq"]), project(params["Wk"]),
               project(params["Wv"]))
    impls = {"ring": ring_attention, "ulysses": ulysses_attention}
    if impl not in impls:
        raise ValueError(f"impl must be one of {sorted(impls)}, "
                         f"got '{impl}'")
    o = impls[impl](q, k, v, mesh=mesh, axis=axis, causal=causal)
    B, H_, T, d = o.shape
    o = o.transpose(0, 2, 1, 3).reshape(B, T, H_ * d)
    out = jnp.einsum("bto,op->btp", o, params["Wo"]) + params["b"]
    return layer.act()(out)
