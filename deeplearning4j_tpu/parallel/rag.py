"""RagPipeline: retrieval-augmented generation as a two-tier fleet flow.

ROADMAP item 4 closes here: PR 14's ``EmbeddingIndex`` and the paged
``GenerationServer`` are the two halves of RAG, and this module composes
them behind ONE ``submit() -> Future`` — encode the query, retrieve
top-k from the knn tier, assemble the retrieved passages as a canonical
chunk-aligned prefix, generate on the generate tier::

    submit(prompt_ids, max_tokens) -> Future
        |
    RagPipeline ------------------- rag ledger + rag_ttft/retrieve/e2e
        |                           histograms (zero lost futures)
    ReplicaFleet (roles knn/generate)
        +-- knn tier:      EmbeddingIndex replicas (coalesced search)
        +-- generate tier: GenerationServer replicas (paged decode)

The fleet is the *same* disagg routing machinery the prefill/decode
tiers use — health-weighted scoring, typed shedding, supervised
restart, per-tier autoscaler levers (``tier_stats`` /
``set_tier_active_slots`` / ``FleetTierTarget``) — with the requests
role-pinned via ``submit(tier=...)`` instead of snapshot-staged.

Performance story: the vLLM-lineage prefix machinery (chunk-hashed COW
pages) plus the canonical passage order of
``assemble_passage_prefix`` mean concurrent requests retrieving the
same hot documents dedupe their prefill — popular passages become a de
facto device-resident KV *document cache*, observable through the
headline ``generation_prefix_hits_total`` /
``generation_prefix_tokens_reused_total`` counters (aggregated here as
``stats()["prefix_hits"]``/``["prefix_tokens_reused"]``).

Deadline propagation crosses the tier boundary: one request budget is
armed at submit, the knn dispatch gets the remaining budget, and the
generate dispatch gets what is left *after* retrieval — a request whose
budget died between tiers fails typed ``DeadlineExceeded`` without
costing a decode slot.

Invariant: **zero lost futures.** Every accepted request resolves with
tokens or a typed error from the resilience taxonomy, and the ledger
balances — ``submitted == completed + failed + expired + rejected``
once the pipeline is idle (asserted in tests and the ``serve_rag``
bench).
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import Future
from functools import partial
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from deeplearning4j_tpu.metrics.registry import MetricsRegistry
from deeplearning4j_tpu.parallel.fleet import ReplicaFleet
from deeplearning4j_tpu.parallel.generation import assemble_passage_prefix
from deeplearning4j_tpu.parallel.resilience import (AdmissionController,
                                                    Deadline,
                                                    DeadlineExceeded,
                                                    ServerOverloaded)

__all__ = ["RagPipeline"]


class _RagRequest:
    """One accepted RAG request: the generation call it will become,
    its single end-to-end deadline, and the caller-facing future."""

    __slots__ = ("prompt", "max_tokens", "temperature", "top_k", "seed",
                 "k", "deadline", "future", "t0", "t_retrieved", "docs",
                 "prefix_len", "gen_prompt")

    def __init__(self, prompt, max_tokens, temperature, top_k, seed, k,
                 deadline: Optional[Deadline]):
        self.prompt = prompt
        self.max_tokens = max_tokens
        self.temperature = temperature
        self.top_k = top_k
        self.seed = seed
        self.k = k
        self.deadline = deadline
        self.future: Future = Future()
        self.t0 = time.monotonic()
        self.t_retrieved = 0.0
        self.docs: list = []
        self.prefix_len = 0
        self.gen_prompt = None


class RagPipeline:
    """Two-tier retrieval-augmented generation server.

    ``knn_factory(rid)`` builds a retrieval replica (``EmbeddingIndex``
    or anything with its ``submit(queries, k, deadline_s=) -> Future``
    contract); ``generate_factory(rid)`` builds a generation replica
    (``GenerationServer``). Both tiers live in ONE ``ReplicaFleet``
    with role-pinned routing, so each tier gets health-weighted
    least-loaded scoring, typed shedding, supervised restart, and its
    own autoscaler lever for free.

    ``passages`` is any indexable mapping doc id -> 1-D token ids (a
    list, an array, or a lazy ``__getitem__`` object for corpora too
    big to materialize). ``page_size`` MUST match the generation
    servers' so the assembled prefix is chunk-aligned to their page
    digests.

    >>> rag = RagPipeline(knn_factory, generate_factory, passages,
    ...                   page_size=16, k=4)
    >>> fut = rag.submit(prompt_ids, 32, query_vec=q, deadline_s=5.0)
    >>> tokens = fut.result()       # fut._rag_docs / _rag_prefix_len /
    ...                             # _rag_prompt carry the retrieval
    """

    def __init__(self, knn_factory: Callable[[int], Any],
                 generate_factory: Callable[[int], Any],
                 passages, *, page_size: int = 16, pad_id: int = 0,
                 k: int = 4, encoder=None, knn_replicas: int = 1,
                 generate_replicas: int = 1, max_pending: int = 256,
                 registry: Optional[MetricsRegistry] = None,
                 request_deadline_s: Optional[float] = None,
                 fleet_kw: Optional[dict] = None):
        if int(knn_replicas) < 1 or int(generate_replicas) < 1:
            raise ValueError("each tier needs at least one replica")
        if int(k) < 1:
            raise ValueError(f"k must be >= 1, got {k}")
        if int(page_size) < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        self._passages = passages
        self._ps = int(page_size)
        self._pad_id = int(pad_id)
        self._k = int(k)
        self._encoder = encoder
        self.request_deadline_s = request_deadline_s
        self._kr = int(knn_replicas)
        self._gr = int(generate_replicas)
        roles = ("knn",) * self._kr + ("generate",) * self._gr

        def factory(rid: int):
            if rid < self._kr:
                return knn_factory(rid)
            srv = generate_factory(rid - self._kr)
            if getattr(srv, "role", None) == "unified":
                # unified-behaving server joining the generate tier: tag
                # it so the fleet's role-pinned route matches (the tag
                # changes routing only — no snapshot staging)
                srv.role = "generate"
            return srv

        fkw = dict(fleet_kw or {})
        fkw.setdefault("roles", roles)
        self.fleet = ReplicaFleet(factory,
                                  replicas=self._kr + self._gr, **fkw)
        self.admission = AdmissionController(max_pending)
        self._lock = threading.Lock()
        # drain parking lot: its OWN condition (never nested inside
        # self._lock), exactly EmbeddingIndex._drain_cv's shape
        self._idle = threading.Condition()
        self._inflight: set = set()
        self._closed = False

        self.metrics = registry if registry is not None \
            else MetricsRegistry()
        m = self.metrics
        self._m_submitted = m.counter(
            "rag_submitted_total", "RAG requests offered")
        self._m_completed = m.counter(
            "rag_completed_total", "RAG requests completed with tokens")
        self._m_failed = m.counter(
            "rag_failed_total", "RAG requests failed typed")
        self._m_expired = m.counter(
            "rag_expired_total", "RAG requests failed on deadline")
        self._m_rejected = m.counter(
            "rag_rejected_total", "RAG submits shed before acceptance")
        self._h_retrieve = m.histogram(
            "rag_retrieve_ms", "submit to retrieval-complete (ms)")
        self._h_ttft = m.histogram(
            "rag_ttft_ms", "submit to first generated token (ms)")
        self._h_e2e = m.histogram(
            "rag_e2e_ms", "submit to final token (ms)")
        m.gauge("rag_pending", "accepted-but-unresolved RAG requests",
                fn=lambda: self.admission.pending)
        m.gauge("rag_k", "passages retrieved per request",
                fn=lambda: float(self._k))

    # ---------------------------------------------------------- encoding
    def _encode(self, prompt: np.ndarray) -> np.ndarray:
        enc = self._encoder
        if enc is None:
            raise ValueError(
                "no encoder attached: pass query_vec= explicitly")
        out = enc.output(prompt) if hasattr(enc, "output") else enc(prompt)
        return np.asarray(out, np.float32).ravel()

    # ------------------------------------------------------------ public
    def submit(self, prompt_ids, max_tokens: int, *,
               query_vec=None, k: Optional[int] = None,
               temperature: float = 0.0, top_k: int = 0, seed: int = 0,
               deadline_s: Optional[float] = None) -> Future:
        """One RAG request: retrieve, assemble, generate. Returns a
        Future resolving to the generated ids (exactly what the
        generation tier would return for the assembled prompt — the
        bit-exactness contract vs a non-RAG reference). The retrieval
        metadata rides the future: ``_rag_docs`` (canonical doc order),
        ``_rag_prefix_len`` (shareable prefix tokens), ``_rag_prompt``
        (the full assembled prompt). Raises typed ``ServerOverloaded``
        at the admission watermark and ValueError on caller errors;
        every accepted request resolves typed — never a hang."""
        prompt = np.asarray(prompt_ids, np.int64).ravel()
        if prompt.size < 1:
            raise ValueError("prompt_ids must be a non-empty 1-D id array")
        if int(max_tokens) < 1:
            raise ValueError(f"max_tokens must be >= 1, got {max_tokens}")
        kk = self._k if k is None else int(k)
        if kk < 1:
            raise ValueError(f"k must be >= 1, got {k}")
        if deadline_s is not None and deadline_s <= 0:
            raise ValueError("deadline_s must be positive")
        q = (self._encode(prompt) if query_vec is None
             else np.asarray(query_vec, np.float32).ravel())
        with self._lock:
            if self._closed:
                raise RuntimeError("RagPipeline is closed")
        budget = deadline_s if deadline_s is not None \
            else self.request_deadline_s
        self.admission.acquire()  # raises ServerOverloaded at watermark
        req = _RagRequest(prompt, int(max_tokens), float(temperature),
                          int(top_k), int(seed), kk,
                          None if budget is None else Deadline(budget))
        req.future.add_done_callback(lambda _f: self.admission.release())
        self._m_submitted.inc()
        with self._lock:
            if self._closed:
                self._finish(req, None,
                             RuntimeError("RagPipeline is closed"))
                return req.future
            self._inflight.add(req)
        # tier 1: retrieval, with the remaining budget
        try:
            kfut = self.fleet.submit(
                q[None, :], kk, tier="knn",
                deadline_s=self._remaining(req))
        except Exception as e:
            # typed shed at the knn tier (overloaded/breaker/dark): the
            # request was never accepted downstream — count it rejected
            # and re-raise synchronously, like the servers do
            self._finish(req, None, e, rejected=True)
            raise
        kfut.add_done_callback(partial(self._rag_retrieve_done, req))
        return req.future

    def _remaining(self, req: _RagRequest) -> Optional[float]:
        """Remaining request budget for the next tier dispatch (the
        cross-tier deadline propagation). Clamped above zero so an
        expired-in-flight budget still dispatches once and fails with
        the dispatching tier's typed DeadlineExceeded."""
        if req.deadline is None:
            return None
        rem = req.deadline.remaining()
        return rem if rem > 0.001 else 0.001

    # ----------------------------------------------------- tier boundary
    def _rag_retrieve_done(self, req: _RagRequest, fut: Future) -> None:
        """Knn-tier completion (runs on the index completer or fleet
        threads; on the graftcheck hot list — no host-sync coercions
        here). Routes the request across the tier boundary: observe
        retrieval latency, then assemble + dispatch generation."""
        if fut.cancelled():
            self._finish(req, None, RuntimeError(
                "retrieval attempt cancelled"))
            return
        exc = fut.exception()
        if exc is not None:
            self._finish(req, None, exc)
            return
        req.t_retrieved = time.monotonic()
        self._h_retrieve.observe((req.t_retrieved - req.t0) * 1000.0)
        _dists, ids = fut.result()
        self._rag_assemble_dispatch(req, ids)

    def _rag_assemble_dispatch(self, req: _RagRequest, ids) -> None:
        """Assemble the canonical passage prefix and dispatch the
        generate tier with the post-retrieval remaining budget (on the
        graftcheck hot list — the id/token coercions live in
        ``assemble_passage_prefix``, outside this body)."""
        try:
            prompt, docs, plen = assemble_passage_prefix(
                ids, self._passages, page_size=self._ps,
                pad_id=self._pad_id, query_ids=req.prompt)
            req.gen_prompt = prompt
            req.docs = docs
            req.prefix_len = plen
            if req.deadline is not None and req.deadline.expired():
                raise DeadlineExceeded(
                    "request budget exhausted after retrieval, before "
                    "the generate-tier dispatch")
            gfut = self.fleet.submit(
                prompt, req.max_tokens, tier="generate",
                temperature=req.temperature, top_k=req.top_k,
                seed=req.seed, deadline_s=self._remaining(req))
        except Exception as e:  # noqa: BLE001 — every path resolves typed
            self._finish(req, None, e)
            return
        gfut.add_done_callback(partial(self._rag_generate_done, req))

    def _rag_generate_done(self, req: _RagRequest, fut: Future) -> None:
        """Generate-tier completion (on the graftcheck hot list):
        observe TTFT off the propagated ``_t_first`` stamp and resolve
        the caller future with the generated ids."""
        if fut.cancelled():
            self._finish(req, None, RuntimeError(
                "generation attempt cancelled"))
            return
        exc = fut.exception()
        if exc is not None:
            self._finish(req, None, exc)
            return
        tf = getattr(fut, "_t_first", None)
        if tf is not None and tf > req.t0:
            self._h_ttft.observe((tf - req.t0) * 1000.0)
        self._finish(req, fut.result(), None)

    # --------------------------------------------------------- resolution
    def _finish(self, req: _RagRequest, value, exc,
                *, rejected: bool = False) -> None:
        """Resolve the caller future exactly once and keep the ledger
        balanced: submitted == completed + failed + expired + rejected
        once idle (zero lost futures)."""
        with self._lock:
            linked = req in self._inflight
            self._inflight.discard(req)
        with self._idle:
            self._idle.notify_all()
        if not linked and req.future.done():
            return
        if rejected:
            self._m_rejected.inc()
        elif exc is None:
            self._m_completed.inc()
            self._h_e2e.observe((time.monotonic() - req.t0) * 1000.0)
        elif isinstance(exc, DeadlineExceeded):
            self._m_expired.inc()
        else:
            self._m_failed.inc()
        try:
            if exc is None:
                req.future._rag_docs = req.docs
                req.future._rag_prefix_len = req.prefix_len
                req.future._rag_prompt = req.gen_prompt
                req.future.set_result(value)
            else:
                req.future.set_exception(exc)
        except Exception:  # noqa: BLE001 — caller cancelled: outcome dropped
            pass

    # ---------------------------------------------------------- observers
    def tier_stats(self, role: str) -> dict:
        """Per-tier queue/outcome aggregates (the autoscaler lever's
        observation surface) — delegates to the fleet."""
        return self.fleet.tier_stats(role)

    def set_tier_active_slots(self, role: str, n: int) -> int:
        """Per-tier capacity lever — delegates to the fleet."""
        return self.fleet.set_tier_active_slots(role, n)

    def _prefix_counters(self) -> Tuple[int, int]:
        hits = reused = 0
        for srv in self.fleet.tier_replicas("generate"):
            try:
                pages = srv.stats().get("pages", {})
            except Exception:  # noqa: BLE001 — replica mid-death
                continue
            hits += int(pages.get("prefix_hits", 0))
            reused += int(pages.get("prefix_tokens_reused", 0))
        return hits, reused

    def stats(self) -> dict:
        """RAG ledger + headline document-cache counters + per-tier
        aggregates. Key set/order pinned in tests/test_metrics.py."""
        with self._lock:
            inflight = len(self._inflight)
        hits, reused = self._prefix_counters()
        return {
            "submitted": int(self._m_submitted.value),
            "completed": int(self._m_completed.value),
            "failed": int(self._m_failed.value),
            "expired": int(self._m_expired.value),
            "rejected": int(self._m_rejected.value),
            "inflight": inflight,
            "k": self._k,
            "page_size": self._ps,
            "prefix_hits": hits,
            "prefix_tokens_reused": reused,
            "tiers": {"knn": self.fleet.tier_stats("knn"),
                      "generate": self.fleet.tier_stats("generate")},
        }

    def metrics_sources(self) -> List[Tuple[Dict[str, str],
                                            MetricsRegistry]]:
        """One-scrape exposition sources: the rag ledger and the fleet
        aggregates unlabeled, each tier replica's registry labeled
        ``tier=knn``/``tier=generate`` — so a single GET /metrics pass
        renders ``rag_ttft_ms`` next to the knn tier's ``knn_recall``
        and the generate tier's prefix counters."""
        out: List[Tuple[Dict[str, str], MetricsRegistry]] = [
            ({}, self.metrics), ({}, self.fleet.metrics)]
        for role in ("knn", "generate"):
            for srv in self.fleet.tier_replicas(role):
                reg = getattr(srv, "metrics", None)
                if reg is not None:
                    out.append(({"tier": role}, reg))
        return out

    # ---------------------------------------------------------- lifecycle
    def drain(self, timeout: Optional[float] = None) -> bool:
        """Block until every accepted RAG request resolved (including
        requests between tiers, which the fleet no longer tracks)."""
        dl = None if timeout is None else Deadline(timeout)
        while True:
            with self._lock:
                if not self._inflight:
                    return True
            if dl is not None and dl.expired():
                return False
            wait = 0.1
            if dl is not None:
                wait = min(wait, max(0.001, dl.remaining()))
            with self._idle:
                self._idle.wait(wait)

    def close(self, timeout: float = 30.0) -> None:
        """Drain, close the fleet, and fail any straggler typed.
        Idempotent; zero lost futures across shutdown."""
        with self._lock:
            already = self._closed
            self._closed = True
        if not already:
            self.drain(timeout)
        self.fleet.close(timeout)
        with self._lock:
            leftovers = list(self._inflight)
        err = RuntimeError("RagPipeline closed with the request in flight")
        for req in leftovers:
            self._finish(req, None, err)

    def __enter__(self) -> "RagPipeline":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
