"""Tensor parallelism: Megatron-style sharded dense pairs over a mesh axis.

No reference equivalent (SURVEY §2.4 checklist: TP absent in DL4J) — this is
the idiomatic TPU extension for models whose weights exceed one chip: the
first dense of a pair is COLUMN-sharded (activations stay sharded, no
communication), the second is ROW-sharded and finishes with ONE ``psum``
over the model axis (Shoeybi et al. 2019). On a 2-D (data, model) mesh this
composes freely with the data-parallel trainer: batch sharded over "data",
weights over "model".

These are building blocks: ``tp_mlp_block`` is the fused two-layer shard_map
pattern. For tensor-parallel training of full networks (MultiLayerNetwork /
ComputationGraph / zoo models) use ``parallel.model_sharding.ShardedTrainer``,
which shards the network's own jitted step via GSPMD instead.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from deeplearning4j_tpu.parallel.mesh import SHARD_MAP_VMA, shard_map_compat

MODEL_AXIS = "model"
DATA_AXIS = "data"


def dp_tp_mesh(data: int, model: int, devices=None) -> Mesh:
    """2-D (data, model) mesh over the first data*model devices."""
    devices = np.asarray(devices if devices is not None
                         else jax.devices()[:data * model])
    return Mesh(devices.reshape(data, model), (DATA_AXIS, MODEL_AXIS))


def tp_mlp_block(x, w1, b1, w2, b2, activation, *, axis: str = MODEL_AXIS):
    """Column-parallel dense -> activation -> row-parallel dense -> psum.

    Call INSIDE shard_map with w1 sharded on its output axis and w2 on its
    input axis (specs from ``tp_specs``). x is replicated across ``axis``;
    the return is too. Exactly one collective (the psum) per block."""
    h = activation(jnp.einsum("bi,ih->bh", x, w1) + b1)  # local columns
    partial_out = jnp.einsum("bh,ho->bo", h, w2)          # local rows
    out = lax.psum(partial_out, axis)
    return out + b2  # b2 replicated; added after the reduction


def tp_specs():
    """PartitionSpecs for (x, w1, b1, w2, b2) of tp_mlp_block."""
    return (P(DATA_AXIS, None), P(None, MODEL_AXIS), P(MODEL_AXIS),
            P(MODEL_AXIS, None), P(None))


def tp_mlp_train_step(mesh: Mesh, activation, loss_fn, lr: float = 0.1):
    """A complete dp x tp sharded training step factory for a 2-layer MLP —
    the minimal end-to-end pattern combining data parallelism (batch sharded
    over 'data', gradients psum-averaged) with tensor parallelism (weights
    sharded over 'model'). Returns a jitted fn
    ``step(params, x, y) -> (params, loss)``."""

    x_spec, w1_spec, b1_spec, w2_spec, b2_spec = tp_specs()
    param_specs = {"w1": w1_spec, "b1": b1_spec, "w2": w2_spec, "b2": b2_spec}

    def local_step(params, x, y):
        def local_loss(p):
            out = tp_mlp_block(x, p["w1"], p["b1"], p["w2"], p["b2"],
                               activation)
            return jnp.mean(loss_fn(out, y))

        loss, grads = jax.value_and_grad(local_loss)(params)
        # The loss is computed (identically) on EVERY model-axis device, so
        # leaves whose cotangents flow through the forward psum arrive
        # n_model-times over-counted — scale by 1/n_model to recover the
        # gradient of the single logical loss. Which leaves: under the
        # VMA-tracking shard_map every leaf; under the legacy check_rep
        # tracker only the MODEL_AXIS-sharded ones (it dedups the cotangents
        # of replicated leaves like b2 itself; measured, jax 0.4.x).
        n_model = lax.psum(1, MODEL_AXIS)
        grads = {
            k: g / n_model
            if SHARD_MAP_VMA or MODEL_AXIS in param_specs[k] else g
            for k, g in grads.items()
        }
        # DP reduction: every leaf is averaged over the data axis. TP needs
        # no further gradient collective: each device owns its weight shard.
        grads = lax.pmean(grads, DATA_AXIS)
        # replicated leaves (b2) carry identical grads across model now
        loss = lax.pmean(lax.pmean(loss, DATA_AXIS), MODEL_AXIS)
        new_params = jax.tree_util.tree_map(lambda p, g: p - lr * g, params,
                                            grads)
        return new_params, loss

    # check_vma stays ON: with it off, the transpose of the forward psum is
    # mis-typed (replicated cotangents get re-summed) and sharded-weight
    # gradients come out wrong — VMA tracking inserts the correct
    # pbroadcast/psum pairing for the backward pass.
    fn = shard_map_compat(
        local_step, mesh=mesh,
        in_specs=(param_specs, x_spec, P(DATA_AXIS, None)),
        out_specs=(param_specs, P()))
    return jax.jit(fn)
