"""Parallelism: mesh data-parallel training, sharded inference, distributed eval.

Replaces the reference's entire scale-out stack (deeplearning4j-scaleout/) with
ICI-mesh collectives: ParallelWrapper's replica threads + averaging
(parallelism/ParallelWrapper.java:53,148-305), the SHARED_GRADIENTS accumulator
path (SymmetricTrainer.java:23-88), and Spark parameter averaging
(spark/.../ParameterAveragingTrainingMaster.java:367-490) all become ONE jitted
sharded step over a jax.sharding.Mesh — `shard_map` + `lax.pmean`. Multi-host
(the Spark-cluster / Aeron-parameter-server role) is the same code over a mesh
spanning hosts after `jax.distributed.initialize` (see distributed.py).
"""

from deeplearning4j_tpu.parallel.trainer import ParallelWrapper
from deeplearning4j_tpu.parallel.mesh import data_model_mesh
from deeplearning4j_tpu.parallel.model_sharding import (
    ShardedTrainer,
    network_param_specs,
    shard_network,
)
from deeplearning4j_tpu.parallel.inference import ParallelInference
from deeplearning4j_tpu.parallel.generation import GenerationServer
from deeplearning4j_tpu.parallel.fleet import ReplicaFleet
from deeplearning4j_tpu.parallel.resilience import (
    AdmissionController,
    ChaosPolicy,
    CircuitBreaker,
    CircuitOpen,
    Deadline,
    DeadlineExceeded,
    ReplicaKilled,
    ReplicaUnavailable,
    ResilienceError,
    RetryPolicy,
    ServerOverloaded,
    StreamStalled,
    TransientDispatchError,
)
from deeplearning4j_tpu.parallel.evaluation import evaluate_on_mesh
from deeplearning4j_tpu.parallel.mesh import data_mesh
from deeplearning4j_tpu.parallel.spark import (
    ParameterAveragingTrainingMaster,
    SparkComputationGraph,
    SparkDl4jMultiLayer,
    TrainingMaster,
)
from deeplearning4j_tpu.parallel.parameter_server import (
    ParameterServer,
    ParameterServerClient,
    ParameterServerParallelWrapper,
    ParameterServerTrainer,
)
from deeplearning4j_tpu.parallel.early_stopping import (
    EarlyStoppingParallelTrainer,
)
from deeplearning4j_tpu.parallel.pipeline import PipelineTrainer
from deeplearning4j_tpu.parallel.elastic import (
    CheckpointListener,
    CheckpointStore,
    FailureDetector,
    FaultInjectionListener,
    FaultTolerantTrainer,
    Heartbeat,
)
