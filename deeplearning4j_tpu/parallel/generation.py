"""Continuous-batching generation serving: slot-pooled KV caches.

``sample_generate`` compiles a whole decode into one program per request —
great latency for ONE caller, but N concurrent callers run N programs
back-to-back: a long request head-of-line-blocks everything behind it and
every step does batch-1 matmuls. ``GenerationServer`` applies
iteration-level (continuous) batching — Orca (Yu et al., OSDI '22) — over
a fixed pool of S decode slots backed by ONE pre-allocated KV-cache pytree
of shape ``[S, ...]`` (the dense-slot special case of vLLM's paged pool,
Kwon et al., SOSP '23):

- ONE compiled decode step advances ALL active sequences per iteration.
  Per-slot stream positions ride in the carry as a ``[S]`` vector (the
  attention layer masks each row by its own true length), so empty or
  finished slots compute masked-out garbage and occupancy changes NEVER
  retrace — the step compiles exactly once.
- New requests are admitted into free slots between steps by a compiled
  prefill-into-slot program; prompt lengths are padded onto pow2 buckets
  (``optimize/bucketing.bucket_length``) so prefill has a handful of
  stable shapes. The prompt's padded tail is masked out of attention and
  the slot's length watermark is set to the TRUE prompt length.
- Finished sequences (EOS or max-tokens) retire their slot immediately
  and resolve their ``Future`` — short requests are never held hostage
  by long ones.
- Sampling params (temperature / top_k / rng) are traced per-slot VALUES,
  not static args, so a batch mixing greedy and sampled requests shares
  the same program. Greedy rows take the same argmax op
  ``_device_generate`` compiles, so greedy outputs are bit-identical to
  ``greedy_generate``.

The serving posture mirrors ``ParallelInference`` (parallel/resilience.py):
``submit(...) -> Future``, an ``AdmissionController`` watermark on the
waiting queue (``ServerOverloaded`` past it), per-request deadlines checked
between steps (``DeadlineExceeded`` — queued or mid-generation, the slot is
freed either way), a circuit breaker over dispatch health, retries for
transient faults, and a ``drain()``/``close()`` lifecycle that resolves
every outstanding future.

The pooled carry is donated back to each step on every backend (CPU
included — XLA aliases host buffers too), so the cache updates in place:
a decode step writes one column per slot instead of copying S full
caches per iteration.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from concurrent.futures import Future
from typing import Optional

import numpy as np

from deeplearning4j_tpu.optimize.bucketing import bucket_length
from deeplearning4j_tpu.parallel.resilience import (AdmissionController,
                                                    ChaosPolicy,
                                                    CircuitBreaker,
                                                    CircuitOpen, Deadline,
                                                    DeadlineExceeded,
                                                    RetryPolicy)

_UNSET = object()


class _Request:
    __slots__ = ("prompt", "max_tokens", "temperature", "top_k", "seed",
                 "eos_id", "deadline", "future", "tokens", "t_submit")

    def __init__(self, prompt, max_tokens, temperature, top_k, seed,
                 eos_id, deadline):
        self.prompt = prompt
        self.max_tokens = max_tokens
        self.temperature = temperature
        self.top_k = top_k
        self.seed = seed
        self.eos_id = eos_id
        self.deadline = deadline
        self.future = Future()
        self.tokens: list = []
        self.t_submit = time.monotonic()


class GenerationServer:
    """Slot-pooled continuous-batching decode server for a causal LM.

    ``net`` must stream through an explicit KV-cache carry (TransformerLM:
    attention kcache/vcache + positional counters). ``submit`` returns a
    ``concurrent.futures.Future`` resolving to the generated token ids
    (numpy int array, EOS token included when hit).
    """

    def __init__(self, net, vocab: int, *, slots: int = 8,
                 eos_id: Optional[int] = None,
                 max_pending: int = 64,
                 request_deadline_s: Optional[float] = None,
                 min_prefill_bucket: int = 8,
                 retry: Optional[RetryPolicy] = None,
                 breaker: Optional[CircuitBreaker] = None,
                 chaos: Optional[ChaosPolicy] = None):
        if slots < 1:
            raise ValueError(f"slots must be >= 1, got {slots}")
        self.net = net
        self.vocab = int(vocab)
        self.slots = int(slots)
        self.eos_id = eos_id
        self.request_deadline_s = request_deadline_s
        self.min_prefill_bucket = int(min_prefill_bucket)
        self.admission = AdmissionController(max_pending)
        self.retry = retry if retry is not None else RetryPolicy()
        self.breaker = breaker if breaker is not None else CircuitBreaker()
        self._chaos = chaos

        self._cond = threading.Condition()
        self._queue: deque = deque()
        self._slot_req: list = [None] * self.slots
        self._n_active = 0
        self._closing = False
        self._stop = False

        # host mirrors of the per-slot decode state fed to the step
        self._last = np.zeros(self.slots, np.int32)
        self._counts = np.zeros(self.slots, np.int32)
        self._temp = np.zeros(self.slots, np.float32)
        self._topk = np.zeros(self.slots, np.int32)
        self._keys = np.zeros((self.slots, 2), np.uint32)

        self._admitted = 0
        self._expired = 0
        self._retired = 0
        self._completed = 0
        self._failed = 0
        self._retried = 0
        self._prefills = 0
        self._decode_steps = 0
        self._tokens = 0
        self._busy_s = 0.0

        self._capacity = None
        self._carry = self._fresh_pool()
        if self._carry is None:
            raise ValueError(
                "net has no seedable streaming KV carry — GenerationServer "
                "serves KV-cache streaming language models (TransformerLM)")
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="generation-server")
        self._thread.start()

    # ----------------------------------------------------------- programs
    def _fresh_pool(self):
        """ONE pre-allocated pooled carry of leading dim ``slots``; the
        per-vertex scalar stream counters become [S] vectors so every
        slot decodes at its own depth inside one program."""
        import jax
        import jax.numpy as jnp

        net = self.net
        net.rnn_clear_previous_state()
        seed = net._seed_streaming_carry(self.slots)
        self._capacity = net._stream_capacity
        net.rnn_clear_previous_state()
        if not seed:
            return None
        pool = {}
        for vname, vdict in seed.items():
            pool[vname] = {
                k: (jnp.zeros((self.slots,), jnp.int32) if k == "cache_pos"
                    else v)
                for k, v in vdict.items()}
        return jax.device_put(pool)

    def _donate(self):
        # the pooled carry (arg 2 of both programs) is donated back every
        # dispatch so the KV pool updates IN PLACE — without it each step
        # copies every cache leaf just to rewrite one column. XLA treats
        # an un-donatable buffer as copy + warning, never an error, and
        # CPU/TPU both alias here (verified: same buffer pointer back)
        return (2,)

    def _decode_program(self):
        """The single decode step: one-hot feedback of each slot's last
        token, one streaming forward over the pool, traced per-slot
        sampling. Compiled ONCE — occupancy, positions, and sampling
        params are all data, not shape."""
        import jax
        import jax.numpy as jnp

        from deeplearning4j_tpu.models.zoo import (lm_stream_forward,
                                                   sampled_next_token)

        net, vocab = self.net, self.vocab
        key = ("gen_decode", self.slots, vocab)

        def build():
            fwd = lm_stream_forward(net)
            dtype = jnp.dtype(net.conf.dtype)

            def step(params, state, carry, last, active, temp, topk,
                     base_keys, counts):
                x = jax.nn.one_hot(last, vocab, dtype=dtype)[:, None, :]
                out, new_carry = fwd(params, state, x, carry)
                # freeze empty slots' stream counters: their garbage
                # writes then land on one fixed column forever instead of
                # drifting toward the cache edge
                for vname, vdict in new_carry.items():
                    if "cache_pos" in vdict:
                        old = carry[vname]["cache_pos"]
                        vdict["cache_pos"] = jnp.where(
                            active, vdict["cache_pos"], old)
                keys = jax.vmap(jax.random.fold_in)(base_keys, counts)
                nxt = sampled_next_token(out[:, 0], keys, temp, topk)
                return new_carry, nxt

            return jax.jit(step, donate_argnums=self._donate())

        return net._get_output(key, build)

    def _prefill_program(self, bucket: int):
        """Prefill-into-slot for one prompt bucket: consume the (right-
        padded, masked) prompt with a fresh batch-1 carry, sample the
        first token from the last TRUE position, scatter the filled
        caches into pool row ``slot`` and set its length watermark to the
        true prompt length. One program per pow2 bucket."""
        import jax
        import jax.numpy as jnp

        from deeplearning4j_tpu.models.zoo import (lm_stream_forward,
                                                   sampled_next_token)

        net, vocab = self.net, self.vocab
        key = ("gen_prefill", self.slots, vocab, bucket)

        def build():
            fwd = lm_stream_forward(net)

            def prefill(params, state, pool, slot, prompt_onehot, mask,
                        plen, temp, topk, base_key):
                one = {}
                for vname, vdict in pool.items():
                    one[vname] = {
                        k: (jnp.zeros((), jnp.int32) if k == "cache_pos"
                            else jnp.zeros((1,) + v.shape[1:], v.dtype))
                        for k, v in vdict.items()}
                out, c1 = fwd(params, state, prompt_onehot, one, mask)
                probs = out[0, plen - 1]
                k0 = jax.random.fold_in(base_key, 0)
                first = sampled_next_token(probs[None], k0[None],
                                           temp[None], topk[None])[0]
                new_pool = {}
                for vname, vdict in pool.items():
                    nv = {}
                    for k, v in vdict.items():
                        if k == "cache_pos":
                            nv[k] = v.at[slot].set(plen)
                        else:
                            nv[k] = v.at[slot].set(c1[vname][k][0])
                    new_pool[vname] = nv
                return new_pool, first

            return jax.jit(prefill, donate_argnums=self._donate())

        return net._get_output(key, build)

    # ------------------------------------------------------------- submit
    def submit(self, prompt_ids, max_tokens: int, *,
               temperature: float = 0.0, top_k: int = 0, seed: int = 0,
               eos_id=_UNSET, deadline_s: Optional[float] = None) -> Future:
        """Queue one generation request; returns a Future resolving to
        the generated ids ([<= max_tokens] numpy int array — shorter when
        the per-request ``eos_id`` / server default is produced, which is
        included). Raises ``ServerOverloaded`` past the admission
        watermark and ``CircuitOpen`` while dispatches are failing."""
        prompt = np.asarray(prompt_ids)
        if prompt.ndim != 1 or prompt.shape[0] < 1:
            raise ValueError(f"prompt_ids must be a non-empty 1-D id "
                             f"array, got shape {prompt.shape}")
        if max_tokens < 1:
            raise ValueError(f"max_tokens must be >= 1, got {max_tokens}")
        if temperature < 0:
            raise ValueError(f"temperature must be >= 0, got {temperature}")
        if top_k < 0 or top_k > self.vocab:
            raise ValueError(f"top_k must be in [0, {self.vocab}], "
                             f"got {top_k}")
        plen = int(prompt.shape[0])
        bucket = bucket_length(plen, minimum=self.min_prefill_bucket,
                               maximum=self._capacity)
        if self._capacity is not None:
            needed = max(bucket, plen + int(max_tokens) - 1)
            if needed > self._capacity:
                raise ValueError(
                    f"prompt + generated positions ({needed}) exceed the "
                    f"KV-cache capacity ({self._capacity}); raise "
                    "SelfAttentionLayer.max_cache or lower max_tokens")
        with self._cond:
            if self._closing:
                raise RuntimeError("GenerationServer is closed")
        if not self.breaker.allow():
            raise CircuitOpen("circuit breaker is open: recent decode "
                              "dispatches failed above threshold")
        budget = deadline_s if deadline_s is not None \
            else self.request_deadline_s
        req = _Request(prompt.astype(np.int64), int(max_tokens),
                       float(temperature), int(top_k), int(seed),
                       self.eos_id if eos_id is _UNSET else eos_id,
                       None if budget is None else Deadline(budget))
        self.admission.acquire()  # raises ServerOverloaded at watermark
        req.future.add_done_callback(lambda _f: self.admission.release())
        with self._cond:
            if self._closing:
                # lost the race with close(): fail typed, not hung
                self._fail(req, RuntimeError("GenerationServer is closed"))
                return req.future
            self._queue.append(req)
            self._cond.notify_all()
        return req.future

    # ---------------------------------------------------------- the loop
    def _loop(self):
        while True:
            with self._cond:
                if self._stop:
                    return
                if not self._queue and self._n_active == 0:
                    self._cond.wait(timeout=0.5)
                    continue
            try:
                self._admit_free_slots()
                with self._cond:
                    n_active = self._n_active
                if n_active:
                    t0 = time.monotonic()
                    self._decode_once()
                    with self._cond:
                        self._busy_s += time.monotonic() - t0
                self._expire_active()
            except Exception as e:  # noqa: BLE001 — a loop death would
                # hang every outstanding future; fail them typed instead
                self._fail_all(e)

    def _pop_admittable(self):
        """Next queued request still worth prefilling (expired ones fail
        typed on the way)."""
        with self._cond:
            while self._queue:
                req = self._queue.popleft()
                if req.deadline is not None and req.deadline.expired():
                    self._expired += 1
                    self._fail(req, DeadlineExceeded(
                        "request budget exhausted while queued "
                        f"({-req.deadline.remaining() * 1e3:.1f} ms over)"))
                    continue
                return req
        return None

    def _admit_free_slots(self):
        for s in range(self.slots):
            if self._slot_req[s] is not None:
                continue
            req = self._pop_admittable()
            if req is None:
                return
            try:
                self._prefill_into(s, req)
            except Exception as e:  # noqa: BLE001 — typed failure for
                # this request only; the slot stays free for the next one
                with self._cond:
                    if isinstance(e, DeadlineExceeded):
                        self._expired += 1
                    else:
                        self._failed += 1
                self._fail(req, e)

    def _prefill_into(self, slot: int, req: _Request):
        import jax

        plen = int(req.prompt.shape[0])
        bucket = bucket_length(plen, minimum=self.min_prefill_bucket,
                               maximum=self._capacity)
        prog = self._prefill_program(bucket)
        dtype = np.dtype(self.net.conf.dtype)
        onehot = np.zeros((1, bucket, self.vocab), dtype)
        onehot[0, np.arange(plen), req.prompt] = 1
        mask = np.zeros((1, bucket), np.float32)
        mask[0, :plen] = 1
        base_key = np.asarray(jax.random.PRNGKey(req.seed), np.uint32)
        dispatch = prog if self._chaos is None else self._chaos.wrap(prog)

        def attempt():
            try:
                out = dispatch(self.net.params, self.net.state, self._carry,
                               np.int32(slot), onehot, mask, np.int32(plen),
                               np.float32(req.temperature),
                               np.int32(req.top_k), base_key)
            except Exception:
                self.breaker.record_failure()
                raise
            self.breaker.record_success()
            return out

        t0 = time.monotonic()
        new_pool, first = self.retry.call(attempt, deadline=req.deadline,
                                          on_retry=self._count_retry)
        self._carry = new_pool
        tok = int(first)
        self._last[slot] = tok
        self._counts[slot] = 1
        self._temp[slot] = req.temperature
        self._topk[slot] = req.top_k
        self._keys[slot] = base_key
        req.tokens.append(tok)
        with self._cond:
            self._busy_s += time.monotonic() - t0
            self._prefills += 1
            self._slot_req[slot] = req
            self._n_active += 1
            self._admitted += 1
            self._tokens += 1
        if self._finished(req, tok):
            self._retire(slot, req)

    def _decode_once(self):
        prog = self._decode_program()
        active = np.array([r is not None for r in self._slot_req])
        dispatch = prog if self._chaos is None else self._chaos.wrap(prog)

        def attempt():
            try:
                out = dispatch(self.net.params, self.net.state, self._carry,
                               self._last, active, self._temp, self._topk,
                               self._keys, self._counts)
            except Exception:
                self.breaker.record_failure()
                raise
            self.breaker.record_success()
            return out

        try:
            new_carry, nxt = self.retry.call(attempt,
                                             on_retry=self._count_retry)
        except Exception as e:  # noqa: BLE001 — carry state is now
            # suspect (possibly donated away): fail the batch typed and
            # restart from a fresh pool so later requests still serve
            self._fail_all(e)
            return
        self._carry = new_carry
        toks = np.asarray(nxt)
        ntok = 0
        for s in range(self.slots):
            req = self._slot_req[s]
            if req is None:
                continue
            tok = int(toks[s])
            req.tokens.append(tok)
            self._counts[s] += 1
            self._last[s] = tok
            ntok += 1
            if self._finished(req, tok):
                self._retire(s, req)
        # ONE condition acquisition per decode step, not one per token
        with self._cond:
            self._decode_steps += 1
            self._tokens += ntok

    def _finished(self, req: _Request, tok: int) -> bool:
        if req.eos_id is not None and tok == req.eos_id:
            return True
        return len(req.tokens) >= req.max_tokens

    def _retire(self, slot: int, req: _Request):
        with self._cond:
            self._slot_req[slot] = None
            self._n_active -= 1
            self._retired += 1
            self._completed += 1
            self._cond.notify_all()
        try:
            req.future.set_result(np.asarray(req.tokens, np.int64))
        except Exception:  # future cancelled/resolved by the caller
            pass

    def _expire_active(self):
        for s in range(self.slots):
            req = self._slot_req[s]
            if req is None or req.deadline is None \
                    or not req.deadline.expired():
                continue
            with self._cond:
                self._slot_req[s] = None
                self._n_active -= 1
                self._expired += 1
                self._cond.notify_all()
            self._fail(req, DeadlineExceeded(
                "request budget exhausted mid-generation after "
                f"{len(req.tokens)} tokens"))

    def _fail(self, req: _Request, exc: BaseException):
        try:
            req.future.set_exception(exc)
        except Exception:  # already resolved/cancelled
            pass

    def _fail_all(self, exc: BaseException):
        """Hard dispatch fault: every in-flight request fails typed (never
        hangs) and the pooled carry is rebuilt from zeros."""
        with self._cond:
            victims = [r for r in self._slot_req if r is not None]
            victims += list(self._queue)
            self._queue.clear()
            self._slot_req = [None] * self.slots
            self._n_active = 0
            self._failed += len(victims)
            self._cond.notify_all()
        for req in victims:
            self._fail(req, exc)
        self._carry = self._fresh_pool()

    def _count_retry(self, attempt, exc):
        with self._cond:
            self._retried += 1

    # --------------------------------------------------------- lifecycle
    def drain(self, timeout: Optional[float] = None) -> bool:
        """Block until every queued and in-flight request has resolved
        (completed, expired, or failed). Returns False on timeout."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while self._queue or self._n_active:
                left = None if deadline is None \
                    else deadline - time.monotonic()
                if left is not None and left <= 0:
                    return False
                self._cond.wait(timeout=0.05 if left is None
                                else min(left, 0.05))
        return True

    def close(self, timeout: float = 30.0) -> None:
        """Stop admitting, drain what is in flight, stop the loop. Any
        request still unresolved past ``timeout`` fails typed — a closed
        server never leaves a hung future behind."""
        with self._cond:
            if self._closing and self._stop:
                return
            self._closing = True
            self._cond.notify_all()
        self.drain(timeout)
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        self._thread.join(timeout=max(timeout, 1.0))
        with self._cond:
            victims = [r for r in self._slot_req if r is not None]
            victims += list(self._queue)
            self._queue.clear()
            self._slot_req = [None] * self.slots
            self._n_active = 0
        for req in victims:
            self._fail(req, RuntimeError("GenerationServer closed with "
                                         "the request still in flight"))

    # ------------------------------------------------------------- stats
    def stats(self) -> dict:
        """Serving counters: the observable surface for /stats, the
        bench, and ops."""
        with self._cond:
            out = {
                "slots": self.slots,
                "active_slots": self._n_active,
                "queued": len(self._queue),
                "admitted": self._admitted,
                "expired": self._expired,
                "retired": self._retired,
                "completed": self._completed,
                "failed": self._failed,
                "retried": self._retried,
                "prefills": self._prefills,
                "decode_steps": self._decode_steps,
                "tokens_generated": self._tokens,
                "tokens_per_s": (self._tokens / self._busy_s
                                 if self._busy_s > 0 else 0.0),
            }
        out.update(accepted=self.admission.accepted,
                   rejected=self.admission.rejected,
                   pending=self.admission.pending,
                   breaker_state=self.breaker.state)
        return out
