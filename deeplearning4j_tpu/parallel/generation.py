"""Continuous-batching generation serving over a PAGED KV-cache pool.

``sample_generate`` compiles a whole decode into one program per request —
great latency for ONE caller, but N concurrent callers run N programs
back-to-back. ``GenerationServer`` applies iteration-level (continuous)
batching — Orca (Yu et al., OSDI '22) — over a fixed pool of S decode
slots, and stores every slot's KV cache in a shared pool of fixed-size
PAGES behind a block table (vLLM, Kwon et al., SOSP '23):

- The device carry is ONE donated pytree of ``[pages, H, page_size, d]``
  K/V pools per attention layer. A host-owned ``[S, max_pages]`` int32
  block table maps each slot to its page list and rides into every
  dispatch as DATA, so HBM cost is proportional to tokens actually
  resident — not slots x worst-case length — and occupancy churn, page
  churn, or sharing changes NEVER retrace. Page 0 is reserved as the
  garbage page that inactive slots harmlessly write into.
- PREFIX SHARING: prompts are hashed per page-aligned chunk with a
  chained digest; a prompt whose leading chunks match pages already
  resident shares them refcounted read-only and prefills only its
  suffix. Shared (or prefix-cache-registered) pages are copy-on-write:
  the first divergent write — including a request's own first decode
  token landing in its registered tail page — copies the page off with
  a tiny compiled page-copy program and repoints the block table.
- One compiled decode program advances all active slots by
  ``steps_per_dispatch`` micro-steps (a ``lax.scan``) per host round
  trip, with ONE batched token fetch — the serial key schedule
  (``fold_in(base_key, token_index)``) makes the result bit-identical
  to ``greedy_generate``/``sample_generate`` token-for-token.
- SPECULATIVE DECODING (``draft_net`` + ``spec_k``): a small draft model
  with a dense slot cache proposes K-1 tokens per slot under the SAME
  key schedule, and the target verifies all K positions in one chunked
  paged dispatch. Emitted tokens are always the TARGET's selections
  under the serial schedule, so outputs are bit-exact regardless of
  draft quality — the draft only buys throughput (accept rate is
  surfaced in ``stats()``).
- Admission is PAGE accounting, not slot counting: ``submit()`` rejects
  a request whose prompt + max_tokens (+ look-ahead margin) cannot fit
  the page budget with a typed ``ServerOverloaded`` up front, and under
  transient pressure the newest slot is preempted — its pages freed, the
  request requeued at the front; the deterministic key schedule makes
  the re-decode bit-identical, so preemption is invisible in outputs.

The serving posture mirrors ``ParallelInference`` (parallel/resilience.py):
``submit(...) -> Future``, an ``AdmissionController`` watermark on the
waiting queue, per-request deadlines checked between steps, a circuit
breaker over dispatch health, retries for transient faults, and a
``drain()``/``close()`` lifecycle that resolves every outstanding future.
"""

from __future__ import annotations

import hashlib
import threading
import time
from collections import OrderedDict, deque
from concurrent.futures import Future
from concurrent.futures import TimeoutError as FutureTimeout
from typing import Optional

import numpy as np

from deeplearning4j_tpu.metrics.registry import MetricsRegistry
from deeplearning4j_tpu.optimize.bucketing import bucket_length, bucket_pages
from deeplearning4j_tpu.parallel.handoff import (WIRE_VERSION, KVSnapshot,
                                                 RequestMigrated,
                                                 SnapshotInvalid,
                                                 SnapshotUnavailable,
                                                 SnapshotUnsupported,
                                                 corrupt_snapshot,
                                                 pack_snapshot,
                                                 padded_payload,
                                                 truncate_snapshot)
from deeplearning4j_tpu.parallel.resilience import (AdmissionController,
                                                    ChaosPolicy,
                                                    CircuitBreaker,
                                                    CircuitOpen, Deadline,
                                                    DeadlineExceeded,
                                                    RetryPolicy,
                                                    ServerOverloaded)
from deeplearning4j_tpu.parallel.runtime import (CLOSED, DRAINING,
                                                 LoopCrashed, ServingLoop,
                                                 supervisor)

_UNSET = object()

#: pool page 0 never backs real tokens: inactive slots' block-table rows
#: are all zeros, so their masked garbage writes land here
GARBAGE_PAGE = 0


def assemble_passage_prefix(doc_ids, passages, *, page_size: int,
                            pad_id: int = 0, query_ids=None):
    """Assemble retrieved passages into a canonical chunk-aligned prompt
    prefix — the admission contract that turns the prefix cache into a
    device-resident document cache.

    Two rules make the page digests collide exactly when the content
    does (``_match_prefix`` hashes ``page_size`` chunks under a chained
    digest, so byte-identical leading pages are the sharing unit):

    - **Canonical order.** Retrieved doc ids are deduplicated and
      sorted ascending, so every request hitting the same documents
      assembles the same byte stream regardless of retrieval-score
      order. Under a skewed (Zipf) query mix the hot documents sort
      first, giving concurrent requests long shared leading runs.
    - **Chunk alignment.** Each passage is padded to a ``page_size``
      multiple with ``pad_id``, so a passage always starts on a page
      boundary and its pages hash identically no matter which
      passages precede it in the shared run.

    Negative ids (IVF empty-slot padding) are dropped. ``query_ids``
    (the user's own prompt tokens) are appended unpadded after the
    prefix — they are per-request and never shared.

    Returns ``(prompt_ids int64, doc_order, prefix_len)``: the full
    prompt, the canonical doc order actually assembled, and how many
    leading tokens are shareable passage prefix."""
    ps = int(page_size)
    if ps < 1:
        raise ValueError(f"page_size must be >= 1, got {page_size}")
    order = sorted({int(i) for i in np.asarray(doc_ids).ravel()
                    if int(i) >= 0})
    parts = []
    for d in order:
        p = np.asarray(passages[d], np.int64).ravel()
        if p.size == 0:
            continue
        pad = -p.size % ps
        if pad:
            p = np.concatenate([p, np.full(pad, int(pad_id), np.int64)])
        parts.append(p)
    prefix = np.concatenate(parts) if parts else np.zeros(0, np.int64)
    plen = int(prefix.size)
    if query_ids is not None:
        q = np.asarray(query_ids, np.int64).ravel()
        prompt = np.concatenate([prefix, q]) if plen else q
    else:
        prompt = prefix
    return prompt, order, plen


class _Request:
    __slots__ = ("prompt", "max_tokens", "temperature", "top_k", "seed",
                 "eos_id", "deadline", "future", "tokens", "t_submit",
                 "snapshot", "export_kv")

    def __init__(self, prompt, max_tokens, temperature, top_k, seed,
                 eos_id, deadline):
        self.prompt = prompt
        self.max_tokens = max_tokens
        self.temperature = temperature
        self.top_k = top_k
        self.seed = seed
        self.eos_id = eos_id
        self.deadline = deadline
        self.future = Future()
        self.tokens: list = []
        self.t_submit = time.monotonic()
        # a KVSnapshot to resume from instead of prefilling from token 0
        # (set by adopt_request and by a preemption that saved its state)
        self.snapshot = None
        # disaggregated prefill tier: after prefill, export the slot as
        # a KVSnapshot (the future's RESULT) instead of decoding here
        self.export_kv = False


class _PagePool:
    """Host-side accounting for the device page pool: a free stack,
    per-page refcounts, and an LRU prefix cache mapping chained content
    digests to resident pages. Page 0 is the reserved garbage page.
    Owned by the serving loop thread — like ``_slot_req``, never locked;
    ``stats()`` reads are racy-but-atomic snapshots."""

    def __init__(self, pages: int):
        self.total = int(pages)
        self.free = list(range(self.total - 1, 0, -1))  # pop() -> page 1
        self.ref = [0] * self.total
        self.cache: OrderedDict = OrderedDict()  # digest -> page (LRU)
        self.tag: dict = {}                      # page -> digest
        self.evictions = 0
        self.peak = 0

    def in_use(self) -> int:
        """Pages holding live data: refcounted by a slot OR retained by
        the prefix cache (reclaimable, but resident)."""
        return self.total - 1 - len(self.free)

    def alloc(self) -> Optional[int]:
        """One page at refcount 1, evicting the oldest reclaimable
        cached page when the free list is dry; None when exhausted."""
        if not self.free:
            for digest, page in list(self.cache.items()):  # oldest first
                if self.ref[page] == 0:
                    self._uncache(digest, page)
                    self.evictions += 1
                    break
        if not self.free:
            return None
        page = self.free.pop()
        self.ref[page] = 1
        self.peak = max(self.peak, self.in_use())
        return page

    def _uncache(self, digest: bytes, page: int) -> None:
        del self.cache[digest]
        del self.tag[page]
        if self.ref[page] == 0:
            self.free.append(page)

    def share(self, page: int) -> None:
        self.ref[page] += 1

    def release(self, page: int) -> None:
        self.ref[page] -= 1
        if self.ref[page] == 0 and page not in self.tag:
            self.free.append(page)

    def protected(self, page: int) -> bool:
        """True when a write to ``page`` must copy first: another slot or
        the prefix cache depends on its current content."""
        return self.ref[page] > 1 or page in self.tag

    def lookup(self, digest: bytes) -> Optional[int]:
        page = self.cache.get(digest)
        if page is not None:
            self.cache.move_to_end(digest)
        return page

    def register(self, digest: bytes, page: int) -> None:
        """Publish ``page`` for future prefix matches. No-op when the
        digest is already cached (the pristine original wins — a COW
        copy of it is about to diverge) or the page already tagged."""
        if digest in self.cache or page in self.tag:
            return
        self.cache[digest] = page
        self.tag[page] = digest

    def shared_count(self) -> int:
        return sum(1 for r in self.ref if r > 1)

    def refcounted(self) -> int:
        return sum(1 for r in self.ref if r > 0)


class GenerationServer:
    """Paged continuous-batching decode server for a causal LM.

    ``net`` must stream through an explicit KV-cache carry (TransformerLM:
    attention kcache/vcache + positional counters); the caches are
    re-homed into a page pool (``init_paged_carry``). ``submit`` returns
    a ``concurrent.futures.Future`` resolving to the generated token ids
    (numpy int array, EOS token included when hit).

    Paging knobs: ``page_size`` tokens per KV page (must divide the
    attention ``max_cache``); ``pages`` total pool pages (default
    ``slots * max_cache/page_size + 1`` — dense-equivalent capacity; set
    lower to serve long-tail workloads in less memory); ``prefix_cache``
    toggles chunk-hash prefix sharing; ``steps_per_dispatch`` decode
    micro-steps fused per host round trip; ``prefill_chunk`` caps the
    tokens a prefill round consumes per row (Sarathi-style chunked
    prefill — long prompts advance through several bounded dispatches
    instead of one huge one, without changing any output bit).

    ``kv_dtype="int8"`` stores the page pool int8 with per-page-row f32
    scales (attention quantizes on write, dequantizes on gather): a
    resident token costs ``2*H*d + 8*H`` bytes instead of
    ``2*H*d*itemsize`` — ~3.5x more tokens per HBM byte at f32 — at the
    price of a bounded greedy-agreement delta instead of bit-exactness
    (the default ``None`` keeps the conf dtype and stays bit-exact).
    COW page copies and the prefix cache carry the scale planes with
    the values, so sharing semantics are unchanged.

    Speculative decoding: pass a small ``draft_net`` (same vocab, its own
    weights, ``max_cache >= `` the target's) and ``spec_k >= 2``; each
    round the draft proposes ``spec_k - 1`` tokens and the target
    verifies all ``spec_k`` positions in one chunked dispatch. Bit-exact
    with the non-speculative paths by construction.
    """

    # Decode-loop-owned state (conc-loop-ownership, see
    # analysis/concurrency_rules.py): every write happens under ``_cond``
    # but the tick thread reads it lock-free between dispatches.
    _LOOP_OWNED = ("_slot_req",)
    _LOOP_LOCK = "_cond"

    #: Class-wide trace lock (rank 28, see analysis/instrument.py):
    #: fleet replica groups share ONE net object but carry per-replica
    #: meshes, so the layer-knob push (paged_mesh / paged_attention) and
    #: the trace that bakes it into a program must be atomic against a
    #: sibling server tracing concurrently. Acquired with no other lock
    #: held; a build never touches ``_cond``.
    _trace_lock = threading.Lock()

    def __init__(self, net, vocab: int, *, slots: int = 8,
                 eos_id: Optional[int] = None,
                 max_pending: int = 64,
                 request_deadline_s: Optional[float] = None,
                 min_prefill_bucket: int = 8,
                 prefill_chunk: int = 256,
                 page_size: int = 16,
                 pages: Optional[int] = None,
                 prefix_cache: bool = True,
                 steps_per_dispatch: int = 4,
                 kv_dtype: Optional[str] = None,
                 paged_attention: Optional[str] = None,
                 mesh=None,
                 tp: Optional[int] = None,
                 draft_net=None,
                 spec_k: int = 4,
                 snapshot_every: int = 0,
                 role: str = "unified",
                 retry: Optional[RetryPolicy] = None,
                 breaker: Optional[CircuitBreaker] = None,
                 chaos: Optional[ChaosPolicy] = None,
                 registry: Optional[MetricsRegistry] = None):
        if slots < 1:
            raise ValueError(f"slots must be >= 1, got {slots}")
        if page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        if steps_per_dispatch < 1:
            raise ValueError(f"steps_per_dispatch must be >= 1, got "
                             f"{steps_per_dispatch}")
        if prefill_chunk < 1:
            raise ValueError(f"prefill_chunk must be >= 1, got "
                             f"{prefill_chunk}")
        self.net = net
        self.vocab = int(vocab)
        self.slots = int(slots)
        self.eos_id = eos_id
        self.request_deadline_s = request_deadline_s
        self.min_prefill_bucket = int(min_prefill_bucket)
        self.prefill_chunk = int(prefill_chunk)
        if kv_dtype not in (None, "int8"):
            raise ValueError(f"unsupported kv_dtype {kv_dtype!r} "
                             "(None or 'int8')")
        # paged-attention read backend (the PagedAttentionHelper seam):
        # None leaves each layer's own ``paged_attention`` knob in place;
        # "auto"/"xla"/"pallas" is pushed onto every paged layer in
        # _probe_net. The RESOLVED backend tags every serving program
        # cache key so xla/pallas families never share traces.
        if paged_attention not in (None, "auto", "xla", "pallas"):
            raise ValueError(
                f"unsupported paged_attention {paged_attention!r} "
                "(None, 'auto', 'xla' or 'pallas')")
        self.paged_attention = paged_attention
        self.prefix_cache = bool(prefix_cache)
        self.steps_per_dispatch = int(steps_per_dispatch)
        self.kv_dtype = kv_dtype
        self._kv_quant = kv_dtype == "int8"
        self.spec_k = int(spec_k)
        # crash-durable serving: every `snapshot_every` generated tokens
        # a long-running slot's KV state is exported to a KVSnapshot and
        # attached to its future (0 = off). The draft's dense cache is
        # not part of the wire format, so speculative servers cannot
        # snapshot.
        self.snapshot_every = max(0, int(snapshot_every))
        if self.snapshot_every and draft_net is not None:
            raise ValueError(
                "snapshot_every is incompatible with draft_net: the "
                "speculative draft's dense KV cache is not part of the "
                "KVSnapshot wire format")
        # disaggregated serving tier. "prefill": submits default to
        # export_kv=True — chunked wave prefill runs to completion, then
        # the request ships out as a KVSnapshot (the future's result)
        # instead of entering the decode loop. "decode": a tier label
        # for routers; the server itself serves adoptions AND plain
        # submits (the token-0 fallback target). "unified": classic
        # co-located serving.
        if role not in ("unified", "prefill", "decode", "generate"):
            raise ValueError(f"role must be 'unified', 'prefill', "
                             f"'decode' or 'generate', got {role!r}")
        if role == "prefill" and draft_net is not None:
            raise ValueError(
                "role='prefill' is incompatible with draft_net: the "
                "exported KVSnapshot cannot carry the draft's dense "
                "KV cache")
        self.role = role
        self.admission = AdmissionController(max_pending)
        self.retry = retry if retry is not None else RetryPolicy()
        self.breaker = breaker if breaker is not None else CircuitBreaker()
        self._chaos = chaos

        # tensor-parallel decode: the paged KV pool shards head-parallel
        # over the mesh's "model" axis ([P, H/tp, ps, d] per chip) while
        # weights, activations and the host-owned block table stay
        # replicated — the only collective in the whole decode step is
        # an exact all-gather of disjoint per-head contexts, so outputs
        # are bit-identical to the single-chip path at every tp.
        # ``tp=`` is the convenience spelling (builds a model_mesh over
        # the first tp devices); an explicit ``mesh=`` wins and lets a
        # fleet pin each replica group to its own device subset.
        # tp in (None, 1) keeps the single-chip path byte-for-byte.
        from deeplearning4j_tpu.parallel.mesh import (MODEL_AXIS,
                                                      MeshGeometryError,
                                                      model_mesh)
        if mesh is None and tp is not None and int(tp) != 1:
            mesh = model_mesh(int(tp))
        if mesh is not None:
            if MODEL_AXIS not in mesh.axis_names:
                raise MeshGeometryError(
                    f"GenerationServer mesh needs a {MODEL_AXIS!r} axis "
                    f"to shard KV heads over, got axes {mesh.axis_names}")
            if tp is not None and int(tp) != mesh.shape[MODEL_AXIS]:
                raise MeshGeometryError(
                    f"tp={tp} disagrees with the mesh's "
                    f"{mesh.shape[MODEL_AXIS]}-way {MODEL_AXIS!r} axis")
        self._mesh = None if (mesh is None
                              or mesh.shape[MODEL_AXIS] == 1) else mesh
        self._tp = 1 if self._mesh is None \
            else int(self._mesh.shape[MODEL_AXIS])

        self._ps = int(page_size)
        # prefill rounds advance at most this many (page-aligned) tokens
        # per dispatch, bounding the transient [S, chunk, ...] prefill
        # activations regardless of prompt length
        self._chunk_cap = max(self._ps,
                              self.prefill_chunk // self._ps * self._ps)
        self._probe_net()
        if pages is None:
            pages = self.slots * self._np + 1
        self.pages_total = int(pages)
        # the pool may be SMALLER than slots x full capacity (that is the
        # point: HBM ∝ resident tokens) — submit() rejects any single
        # request the budget cannot cover, and transient multi-slot
        # pressure preempts the newest slot; only the garbage page plus
        # one usable page are unconditionally required
        if self.pages_total < 2:
            raise ValueError(f"pages={self.pages_total} must be >= 2 "
                             "(the reserved garbage page + one usable)")
        self._page_bytes = self._page_token_bytes * self._ps

        self._draft = draft_net
        self._draft_cap = None
        if draft_net is not None:
            if self.spec_k < 2:
                raise ValueError(f"spec_k must be >= 2 (one verified "
                                 f"chunk needs at least one draft token), "
                                 f"got {self.spec_k}")
            self._probe_draft()
        # decode-write look-ahead per dispatch: M fused micro-steps, or
        # the K-token speculative chunk
        self._lookahead = self.spec_k if draft_net is not None \
            else self.steps_per_dispatch

        self._cond = threading.Condition()
        self._queue: deque = deque()
        self._slot_req: list = [None] * self.slots
        self._n_active = 0
        self._active_cap = self.slots
        # distinguishes a deliberate close() from a crash-forced CLOSED
        # state: the supervisor only restarts the loop when this is False
        self._user_close = False

        # host mirrors of the per-slot decode state fed to the step
        self._last = np.zeros(self.slots, np.int32)
        self._counts = np.zeros(self.slots, np.int32)
        self._temp = np.zeros(self.slots, np.float32)
        self._topk = np.zeros(self.slots, np.int32)
        self._keys = np.zeros((self.slots, 2), np.uint32)
        # host-owned paging state: per-slot positions, block table, and
        # page lists (loop-thread-owned, like _slot_req)
        self._pos = np.zeros(self.slots, np.int32)
        self._bt = np.zeros((self.slots, self._np), np.int32)
        self._slot_pages: list = [[] for _ in range(self.slots)]
        self._slot_seq = [0] * self.slots
        self._admit_seq = 0
        self._page_pool = _PagePool(self.pages_total)
        # handoff state: per-slot token count at the last snapshot, the
        # export handshake queue ((request future, out future) pairs the
        # loop services between dispatches), and the drain-migrate flag
        self._snap_counts = [0] * self.slots
        self._export_q: deque = deque()
        self._migrating = False
        self._migrate_cb = None

        # serving counters live in the (leaf-locked) registry, so the
        # loop thread publishes without ever touching ``_cond`` and a
        # scrape never blocks admission; ``_cond`` only guards queue and
        # slot structure
        self.metrics = registry if registry is not None \
            else MetricsRegistry()
        m = self.metrics
        self._m_admitted = m.counter(
            "generation_admitted_total", "requests committed to a slot")
        self._m_expired = m.counter(
            "generation_expired_total", "requests failed on deadline")
        self._m_retired = m.counter(
            "generation_retired_total", "slots retired")
        self._m_completed = m.counter(
            "generation_completed_total", "requests completed")
        self._m_failed = m.counter(
            "generation_failed_total", "requests failed on error")
        self._m_retried = m.counter(
            "generation_retried_total", "dispatch retries")
        self._m_pool_rebuilds = m.counter(
            "generation_pool_rebuilds_total",
            "device-state rebuilds after a hard dispatch fault")
        self._m_prefills = m.counter(
            "generation_prefills_total", "prompts prefilled")
        self._m_decode_steps = m.counter(
            "generation_decode_steps_total", "decode dispatches")
        self._m_tokens = m.counter(
            "generation_tokens_total", "tokens generated")
        self._m_busy_s = m.counter(
            "generation_busy_seconds_total",
            "wall seconds spent in prefill/decode dispatches")
        self._m_cow_copies = m.counter(
            "generation_cow_copies_total", "copy-on-write page copies")
        self._m_preempted = m.counter(
            "generation_preempted_total",
            "slots preempted under page-pool pressure")
        self._m_prefix_hits = m.counter(
            "generation_prefix_hits_total",
            "prompts that reused a cached prefix")
        self._m_prefix_reused = m.counter(
            "generation_prefix_tokens_reused_total",
            "prompt tokens served from the prefix cache")
        self._m_spec_rounds = m.counter(
            "generation_spec_rounds_total", "speculative decode rounds")
        self._m_spec_proposed = m.counter(
            "generation_spec_proposed_total", "draft tokens proposed")
        self._m_spec_accepted = m.counter(
            "generation_spec_accepted_total", "draft tokens accepted")
        self._m_handoff_snapshots = m.counter(
            "generation_handoff_snapshots_total",
            "KV snapshots exported (periodic, explicit, and migrate)")
        self._m_handoff_bytes = m.counter(
            "generation_handoff_bytes_total",
            "wire bytes of exported KV snapshots")
        self._m_handoff_resumes = m.counter(
            "generation_handoff_resumes_total",
            "requests resumed from an adopted KV snapshot")
        self._m_handoff_saved = m.counter(
            "generation_handoff_tokens_saved_total",
            "decoded tokens NOT regenerated thanks to snapshot resume")
        self._m_handoff_fallbacks = m.counter(
            "generation_handoff_fallbacks_total",
            "adoptions that fell back to token-0 prefill")
        self._m_preempt_resumes = m.counter(
            "generation_handoff_preempt_resumes_total",
            "preemptions that saved a snapshot instead of recomputing")
        self._m_migrated = m.counter(
            "generation_handoff_migrated_total",
            "requests migrated off this server by drain(migrate=...)")
        self._m_prefill_exports = m.counter(
            "generation_prefill_exports_total",
            "requests exported as KVSnapshots after prefill "
            "(disaggregated prefill tier)")
        m.gauge("generation_slots", "decode slot pool size",
                fn=lambda: self.slots)
        m.gauge("generation_active_slots", "slots currently decoding",
                fn=lambda: self._n_active)
        m.gauge("generation_active_slot_cap",
                "autoscaler admission cap on concurrently active slots",
                fn=lambda: self._active_cap)
        m.gauge("generation_queue_depth", "requests waiting for a slot",
                fn=lambda: len(self._queue))
        m.gauge("generation_pending", "admitted-but-unresolved requests",
                fn=lambda: self.admission.pending)
        m.gauge("generation_accepted", "requests accepted by admission",
                fn=lambda: self.admission.accepted)
        m.gauge("generation_rejected", "requests rejected by admission",
                fn=lambda: self.admission.rejected)
        m.gauge("generation_breaker_open",
                "circuit state (0 closed, 0.5 half-open, 1 open)",
                fn=self._breaker_level)
        m.gauge("generation_pages_free", "unallocated KV pages",
                fn=lambda: len(self._page_pool.free))
        m.gauge("generation_pages_cached", "prefix-cache-pinned KV pages",
                fn=lambda: len(self._page_pool.cache))
        m.gauge("generation_resident_kv_bytes", "bytes of resident KV",
                fn=lambda: self._page_pool.in_use() * self._page_bytes)
        # KV-residency telemetry on the Prometheus surface, not just
        # /stats: total/in-use/shared occupancy, the high-water mark,
        # and the cache geometry (bytes/token + int8 flag)
        m.gauge("generation_pages_total", "KV page-pool size "
                "(incl. the reserved garbage page)",
                fn=lambda: self.pages_total)
        m.gauge("generation_pages_in_use",
                "pages holding live data (refcounted or prefix-cached)",
                fn=lambda: self._page_pool.in_use())
        m.gauge("generation_pages_shared",
                "pages refcounted by more than one slot",
                fn=lambda: self._page_pool.shared_count())
        m.gauge("generation_peak_resident_kv_bytes",
                "high-water resident KV bytes",
                fn=lambda: self._page_pool.peak * self._page_bytes)
        m.gauge("generation_kv_bytes_per_token",
                "bytes per resident KV token (values + dequant scales)",
                fn=lambda: self._page_token_bytes)
        m.gauge("generation_kv_cache_int8",
                "1 when pages store int8 (+f32 scales), 0 for conf dtype",
                fn=lambda: 1.0 if self._kv_quant else 0.0)

        self._pool = self._fresh_pool()
        self._dpool = None if draft_net is None else self._fresh_draft_pool()
        self._runtime = ServingLoop("generation-server",
                                    tick=self._tick_once,
                                    wake=self._wake_loop, chaos=chaos)
        self._runtime.start()
        supervisor().watch(self._runtime, on_death=self._on_loop_death,
                           restart=True)

    # ------------------------------------------------- lifecycle state
    @property
    def _closing(self) -> bool:
        """True once the lifecycle left RUNNING (draining or closed)."""
        return self._runtime.state in (DRAINING, CLOSED)

    @property
    def _stop(self) -> bool:
        return self._runtime.state is CLOSED

    def _breaker_level(self) -> float:
        if self.breaker is None:
            return 0.0
        return {"closed": 0.0, "half_open": 0.5,
                "open": 1.0}.get(self.breaker.state, 0.0)

    @property
    def active_slot_cap(self) -> int:
        """Admission cap on concurrently ACTIVE slots. Slot count is
        baked into the compiled program shapes, so autoscaling never
        resizes the pool — it bounds how many slots ``_admit_free_slots``
        may fill, which is retrace-free."""
        with self._cond:
            return self._active_cap

    def set_active_slots(self, n: int) -> int:
        """Clamp and apply a new active-slot admission cap (autoscaler
        hook). Lowering the cap never evicts running requests; it only
        stops new admissions until occupancy falls below the cap."""
        n = max(1, min(int(n), self.slots))
        with self._cond:
            self._active_cap = n
            self._cond.notify_all()
        return n

    def queue_depth(self) -> int:
        with self._cond:
            return len(self._queue)

    # ------------------------------------------------------ introspection
    def _probe_net(self):
        """Classify the net's streaming layers for the paged carry: which
        vertices hold pageable KV caches, which only carry positions —
        and derive the block-table geometry from the KV capacity."""
        net = self.net
        net.rnn_clear_previous_state()
        probe = net._seed_streaming_carry(1)
        cap = net._stream_capacity
        net.rnn_clear_previous_state()
        self._paged_names: list = []
        self._pos_names: list = []
        self._layer_by_name: dict = {}
        self._pa_prev: dict = {}
        self._mesh_prev: dict = {}
        self._page_token_bytes = 0
        # admission accounting must track the CACHE dtype, not the conf
        # dtype: int8 pages store 1-byte values plus one f32 scale per
        # token per head for K and V each (the _fresh_pool allocation
        # cross-checks this against the real array bytes)
        if self._kv_quant:
            kv_itemsize = 1
            scale_bytes = np.dtype(np.float32).itemsize
        else:
            kv_itemsize = np.dtype(net.conf.dtype).itemsize
            scale_bytes = 0
        for name, layer in net._stream_layers():
            c = probe.get(name)
            if not c:
                continue
            self._layer_by_name[name] = layer
            if "kcache" in c and hasattr(layer, "init_paged_carry"):
                if self.paged_attention is not None:
                    # push the server-level knob onto the layer: the
                    # layer resolves it at trace time, so every program
                    # family (prefill / decode / spec verify) routes its
                    # paged reads through the same backend. The prior
                    # knob is restored on close() — a server override
                    # must not leak into a net another server serves
                    # later.
                    self._pa_prev[name] = layer.paged_attention
                    layer.paged_attention = self.paged_attention
                self._paged_names.append(name)
                h = layer.n_heads
                if self._mesh is not None and h % self._tp:
                    from deeplearning4j_tpu.parallel.mesh import (
                        MeshGeometryError)
                    raise MeshGeometryError(
                        f"layer {name!r} has {h} heads, not divisible by "
                        f"tp={self._tp}: the head-parallel pool shard "
                        "[pages, H/tp, page_size, d] would be ragged")
                # record the pre-server mesh knob but do NOT push it
                # here: the push is BUILD-scoped (_get_program sets it
                # under the trace lock and restores it after the trace),
                # so sibling servers with different meshes on this net
                # never see each other's Mesh on the layer. close()
                # restores defensively in case a build hard-crashed.
                self._mesh_prev[name] = layer.paged_mesh
                self._page_token_bytes += 2 * h * (
                    (layer.n_out // h) * kv_itemsize + scale_bytes)
            elif "cache_pos" in c and "kcache" not in c:
                self._pos_names.append(name)
            else:
                raise ValueError(
                    f"layer {name!r} streams through a carry the paged "
                    "pool cannot host (expected attention kcache/vcache "
                    "or a bare cache_pos counter)")
        if not self._paged_names or cap is None:
            raise ValueError(
                "net has no seedable streaming KV carry — GenerationServer "
                "serves KV-cache streaming language models (TransformerLM)")
        if cap % self._ps:
            raise ValueError(
                f"page_size {self._ps} must divide the KV-cache capacity "
                f"{cap} (attention max_cache) so the paged view is bit-"
                "identical to the contiguous cache")
        self._capacity = cap
        self._cap_tokens = cap
        self._np = cap // self._ps
        # resolve the paged-attention backend ONCE against the real pool
        # geometry: this is the program-cache tag (xla/pallas families
        # must never share traces) and picks the decode dispatch family.
        # Resolution is host config + static shapes — never traced data.
        from deeplearning4j_tpu.nn.conf.layers.paged_attention import (
            resolve_paged_backend)
        first = self._layer_by_name[self._paged_names[0]]
        self._pa = resolve_paged_backend(
            first.paged_attention, page_size=self._ps,
            head_dim=first.n_out // first.n_heads, n_pages=self._np,
            quant=self._kv_quant)

    def _probe_draft(self):
        draft = self._draft
        draft.rnn_clear_previous_state()
        probe = draft._seed_streaming_carry(1)
        dcap = draft._stream_capacity
        draft.rnn_clear_previous_state()
        self._d_attn_names: list = []
        self._d_pos_names: list = []
        for name, layer in draft._stream_layers():
            c = probe.get(name)
            if not c:
                continue
            if "kcache" in c:
                self._d_attn_names.append(name)
            elif "cache_pos" in c:
                self._d_pos_names.append(name)
        if not self._d_attn_names or dcap is None:
            raise ValueError("draft_net has no seedable streaming KV "
                             "carry — speculative decoding needs a "
                             "KV-cache streaming draft model")
        if dcap < self._cap_tokens:
            raise ValueError(
                f"draft_net max_cache {dcap} < target capacity "
                f"{self._cap_tokens}: the draft must reach every "
                "position the target can")
        self._draft_cap = dcap

    # ----------------------------------------------------------- programs
    def _fresh_pool(self):
        """The donated device carry: one [pages, H, page_size, d] K/V
        pool per attention layer (plus [pages, H, page_size] f32 scale
        planes under ``kv_dtype="int8"``). Positions and block tables
        are HOST state threaded in per dispatch, so this is all the
        device keeps. The admission bookkeeping's bytes-per-page is
        cross-checked against the REAL allocated array bytes here — the
        two accounting paths are not allowed to diverge."""
        import jax
        import jax.numpy as jnp

        dtype = jnp.dtype(self.net.conf.dtype)
        pool = {name: self._layer_by_name[name].init_paged_carry(
            self.pages_total, self._ps, dtype, kv_dtype=self.kv_dtype)
            for name in self._paged_names}
        nbytes = sum(int(leaf.nbytes)
                     for leaf in jax.tree_util.tree_leaves(pool))
        self._page_bytes_actual = nbytes // self.pages_total
        if self._page_bytes_actual != self._page_bytes:
            raise AssertionError(
                f"KV admission accounting diverged from the allocated "
                f"pool: {self._page_bytes} bytes/page expected from the "
                f"conf, {self._page_bytes_actual} allocated "
                f"(kv_dtype={self.kv_dtype!r})")
        return self._shard_pool(pool)

    def _shard_pool(self, pool):
        """Home the page pool on device: a plain ``device_put`` single-
        chip, or head-axis NamedSharding placement over the tensor-
        parallel mesh — 4-D K/V leaves ``[P, H, ps, d]`` and 3-D int8
        scale planes ``[P, H, ps]`` both split on axis 1, so each chip
        holds a ``[P, H/tp, ps, d]`` slice and the per-chip page budget
        is 1/tp of the single-chip pool. Placement only — on the
        graftcheck hot list, so no host syncs in here."""
        import jax

        if self._mesh is None:
            return jax.device_put(pool)
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        from deeplearning4j_tpu.parallel.mesh import MODEL_AXIS

        head4 = NamedSharding(self._mesh, P(None, MODEL_AXIS, None, None))
        head3 = NamedSharding(self._mesh, P(None, MODEL_AXIS, None))

        def put(leaf):
            return jax.device_put(leaf,
                                  head4 if leaf.ndim == 4 else head3)

        return jax.tree_util.tree_map(put, pool)

    def _reshard_snapshot(self, payload):
        """Adopt-side reshard: place a snapshot's canonical host-layout
        page payload (leaves ``[NP, H, ps, d]`` / ``[NP, H, ps]``) into
        this server's pool sharding before the donated store dispatch,
        so a snapshot exported at any tp scatters straight into a pool
        sharded at THIS server's tp — each chip uploads only its own
        head slice. Single-chip servers pass the payload through
        untouched (the store program's jit places it). On the graftcheck
        hot list: placement only, no host syncs."""
        if self._mesh is None:
            return payload
        return self._shard_pool(payload)

    def _get_program(self, cache_net, key, build):
        """Compile-or-fetch a serving program with the layer knobs
        re-pushed under the class-wide trace lock: the mesh (and the
        paged-attention backend) are baked into the traced program, so
        the push and the trace must be atomic against sibling servers
        sharing this net. Program keys carry the mesh, so per-replica
        families never share traces; cache hits skip the lock
        entirely."""
        def locked_build():
            with GenerationServer._trace_lock:
                saved = {}
                for name in self._paged_names:
                    layer = self._layer_by_name[name]
                    saved[name] = layer.paged_mesh
                    layer.paged_mesh = self._mesh
                    if self.paged_attention is not None:
                        layer.paged_attention = self.paged_attention
                try:
                    return build()
                finally:
                    # build-scoped: the Mesh never outlives the trace,
                    # so the net's layers read as single-chip config
                    # between builds (reference scans, sibling probes)
                    for name, prev in saved.items():
                        self._layer_by_name[name].paged_mesh = prev

        return cache_net._get_output(key, locked_build)

    def _fresh_draft_pool(self):
        """Dense [S, H, cap, d] slot caches for the draft model (the
        draft is small — paging it would buy little and cost a second
        block table)."""
        import jax

        draft = self._draft
        draft.rnn_clear_previous_state()
        seed = draft._seed_streaming_carry(self.slots)
        draft.rnn_clear_previous_state()
        dpool = {name: {"kcache": seed[name]["kcache"],
                        "vcache": seed[name]["vcache"]}
                 for name in self._d_attn_names}
        return jax.device_put(dpool)

    def _decode_program(self):
        """The fused decode dispatch: ``steps_per_dispatch`` micro-steps
        of one-hot feedback + streaming forward + traced per-slot
        sampling, scanned on device so the host pays one round trip per
        M tokens. Compiled ONCE — occupancy, positions, block tables and
        sampling params are all data, not shape.

        The page pool is gathered into a dense ``[S, H, Tmax, d]`` view
        ONCE per dispatch, the M micro-steps run the per-row DENSE
        streaming path over that view (bit-identical math — the view is
        exactly the cache a contiguous layout would hold), and each
        micro-step's freshly written column is scattered into its page
        as it is produced (a one-column in-place scatter inside the
        donated scan — near-free, unlike a bulk read-modify-write at
        dispatch end). Gathering per dispatch instead of per micro-step
        is the difference between paying the page indirection once per M
        tokens and once per token.

        Rows write-clamp at the per-slot capacity: a row whose position
        reaches ``NP * ps`` freezes (token, position, count all hold and
        its column write is routed to the garbage page). Only overshoot
        tokens past a request's ``max_tokens`` can hit the clamp — the
        host truncates those anyway — so admission needs NO look-ahead
        margin and ``steps_per_dispatch`` can exceed a request's
        remaining budget safely.

        Under the ``pallas`` paged-attention backend the dense gather
        disappears entirely: each micro-step threads the pool + block
        table straight through ``_paged_forward``, whose Pallas kernel
        reads K/V pages in place (the whole point of the seam — the
        gather cost at long contexts is what the kernel deletes).
        Frozen rows swap their block-table row for the garbage page so
        the clamped column write cannot clobber real KV at capacity-1;
        their outputs are discarded by the same hold logic either way.
        The two families are keyed apart in the program cache and are
        bit-exact (tests/test_paged_attention.py pins it)."""
        import jax
        import jax.numpy as jnp

        from deeplearning4j_tpu.models.zoo import (lm_stream_forward,
                                                   sampled_next_token)

        net, vocab = self.net, self.vocab
        m_steps = self.steps_per_dispatch
        paged = tuple(self._paged_names)
        pos_only = tuple(self._pos_names)
        quant = self._kv_quant
        pa = self._pa
        key = ("gen_decode", self.slots, vocab, m_steps, self.kv_dtype,
               self._mesh, pa)

        def build():
            fwd = lm_stream_forward(net)
            dtype = jnp.dtype(net.conf.dtype)

            def paged_step(params, state, pool, bt, positions, last,
                           active, temp, topk, base_keys, counts):
                first = next(iter(paged))
                ps = pool[first]["kpages"].shape[2]
                cap = bt.shape[1] * ps

                def body(cs, _):
                    pool, pos, cur, cnt = cs
                    # write-clamp: overshoot rows at capacity freeze,
                    # and their WHOLE block-table row swaps to the
                    # garbage page so the clamped column write lands
                    # there instead of on real KV at capacity-1
                    act = active & (pos < cap)
                    posw = jnp.minimum(pos, cap - 1)
                    bt_eff = jnp.where(act[:, None], bt, 0)
                    carry = {}
                    for vn in pos_only:
                        carry[vn] = {"cache_pos": posw}
                    for vn in paged:
                        carry[vn] = dict(pool[vn])
                        carry[vn]["block_table"] = bt_eff
                        carry[vn]["cache_pos"] = posw
                    x = jax.nn.one_hot(cur, vocab,
                                       dtype=dtype)[:, None, :]
                    out, nc = fwd(params, state, x, carry)
                    pool = {vn: {k: nc[vn][k] for k in pool[vn]}
                            for vn in paged}

                    def _greedy(out0):
                        return jnp.argmax(out0, axis=-1).astype(jnp.int32)

                    def _sampled(out0):
                        keys = jax.vmap(jax.random.fold_in)(base_keys,
                                                            cnt)
                        return sampled_next_token(
                            out0, keys, temp, topk).astype(jnp.int32)

                    nxt = jax.lax.cond(jnp.all(temp <= 0.0),
                                       _greedy, _sampled, out[:, 0])
                    nxt = jnp.where(act, nxt, cur).astype(cur.dtype)
                    pos = jnp.where(act, pos + 1, pos)
                    cnt = jnp.where(act, cnt + 1, cnt)
                    return (pool, pos, nxt, cnt), nxt

                (pool, _, _, _), seq = jax.lax.scan(
                    body, (pool, positions, last, counts), None,
                    length=m_steps)
                return pool, seq.T                         # [S, M]

            def gather(pages, bt):
                S, NP = bt.shape
                return pages[bt].transpose(0, 2, 1, 3, 4).reshape(
                    S, pages.shape[1], NP * pages.shape[2],
                    pages.shape[3])

            def gather_s(planes, bt):
                # scale planes [P, H, ps] -> dense [S, H, NP*ps] strips
                S, NP = bt.shape
                return planes[bt].transpose(0, 2, 1, 3).reshape(
                    S, planes.shape[1], NP * planes.shape[2])

            def step(params, state, pool, bt, positions, last, active,
                     temp, topk, base_keys, counts):
                views = {vn: {"kcache": gather(pool[vn]["kpages"], bt),
                              "vcache": gather(pool[vn]["vpages"], bt)}
                         for vn in paged}
                if quant:
                    for vn in paged:
                        views[vn]["kscale"] = gather_s(
                            pool[vn]["kscales"], bt)
                        views[vn]["vscale"] = gather_s(
                            pool[vn]["vscales"], bt)
                first = next(iter(paged))
                ps = pool[first]["kpages"].shape[2]
                cap = bt.shape[1] * ps

                def body(cs, _):
                    views, pool, pos, cur, cnt = cs
                    # write-clamp: overshoot rows at capacity freeze
                    act = active & (pos < cap)
                    posw = jnp.minimum(pos, cap - 1)
                    carry = {}
                    for vn in pos_only:
                        carry[vn] = {"cache_pos": posw}
                    for vn in paged:
                        carry[vn] = dict(views[vn])
                        carry[vn]["cache_pos"] = posw
                    x = jax.nn.one_hot(cur, vocab, dtype=dtype)[:, None, :]
                    out, nc = fwd(params, state, x, carry)
                    views = {vn: {k: nc[vn][k] for k in views[vn]}
                             for vn in paged}
                    # scatter the column this step wrote into its page:
                    # in-place inside the donated scan. Frozen/inactive
                    # rows land on the garbage page (COW upstream keeps
                    # real targets exclusively owned)
                    pg = jnp.take_along_axis(
                        bt, (posw // ps)[:, None], axis=1)[:, 0]
                    pg = jnp.where(act, pg, 0)
                    off = posw % ps
                    cidx = posw[:, None, None, None]
                    sidx = posw[:, None, None]
                    for vn in paged:
                        kc, vc = views[vn]["kcache"], views[vn]["vcache"]
                        kcol = jnp.take_along_axis(kc, cidx, axis=2)
                        vcol = jnp.take_along_axis(vc, cidx, axis=2)
                        new = {
                            "kpages": pool[vn]["kpages"].at[
                                pg, :, off, :].set(kcol[:, :, 0, :]),
                            "vpages": pool[vn]["vpages"].at[
                                pg, :, off, :].set(vcol[:, :, 0, :])}
                        if quant:
                            # the written column's dequant scales ride
                            # into the pool through the same routing
                            kscol = jnp.take_along_axis(
                                views[vn]["kscale"], sidx, axis=2)
                            vscol = jnp.take_along_axis(
                                views[vn]["vscale"], sidx, axis=2)
                            new["kscales"] = pool[vn]["kscales"].at[
                                pg, :, off].set(kscol[:, :, 0])
                            new["vscales"] = pool[vn]["vscales"].at[
                                pg, :, off].set(vscol[:, :, 0])
                        pool[vn] = new

                    # all-greedy batches skip the PRNG fold-ins and the
                    # full-vocab sort entirely — lax.cond picks the branch
                    # at RUN time, so mixed batches still share this one
                    # program, and the greedy op is the same argmax
                    # sampled_next_token takes for temp<=0 rows (bit-exact)
                    def _greedy(out0):
                        return jnp.argmax(out0, axis=-1).astype(jnp.int32)

                    def _sampled(out0):
                        keys = jax.vmap(jax.random.fold_in)(base_keys, cnt)
                        return sampled_next_token(
                            out0, keys, temp, topk).astype(jnp.int32)

                    nxt = jax.lax.cond(jnp.all(temp <= 0.0),
                                       _greedy, _sampled, out[:, 0])
                    # frozen rows hold: token, position and count all
                    # stall so their garbage stays on the garbage page
                    # (cast: argmax may widen to int64 under x64 mode)
                    nxt = jnp.where(act, nxt, cur).astype(cur.dtype)
                    pos = jnp.where(act, pos + 1, pos)
                    cnt = jnp.where(act, cnt + 1, cnt)
                    return (views, pool, pos, nxt, cnt), nxt

                (_, pool, _, _, _), seq = jax.lax.scan(
                    body, (views, pool, positions, last, counts), None,
                    length=m_steps)
                return pool, seq.T                         # [S, M]

            return jax.jit(paged_step if pa == "pallas" else step,
                           donate_argnums=(2,))

        return self._get_program(net, key, build)

    def _prefill_program(self, bucket: int):
        """Batched suffix prefill for one page-aligned bucket: every
        slot admitted this wave consumes its (right-padded, masked)
        suffix at its shared-prefix offset through ONE paged forward —
        KV lands directly in each slot's pages, weights are read once
        for the whole wave instead of once per request — and samples its
        first token from its last TRUE position. Non-admitted rows
        (free, or mid-decode) ride along as zero rows with their writes
        routed to the garbage page. One program per bucket."""
        import jax
        import jax.numpy as jnp

        from deeplearning4j_tpu.models.zoo import (lm_stream_forward,
                                                   sampled_next_token)

        net, vocab = self.net, self.vocab
        paged = tuple(self._paged_names)
        pos_only = tuple(self._pos_names)
        key = ("gen_prefill", self.slots, vocab, bucket, self.kv_dtype,
               self._mesh, self._pa)

        def build():
            fwd = lm_stream_forward(net)

            def prefill(params, state, pool, bt, pos0, onehot, mask,
                        sufflen, temp, topk, base_keys, admit):
                # non-admitted rows write the garbage page — an active
                # decode slot in the same batch must NOT have its real
                # pages clobbered by its zero-row ride-along
                bt_eff = jnp.where(admit[:, None], bt, 0)
                carry = {}
                for vn in pos_only:
                    carry[vn] = {"cache_pos": pos0}
                for vn in paged:
                    # generic over kv dtypes: int8 pools carry
                    # kscales/vscales planes alongside kpages/vpages
                    carry[vn] = dict(pool[vn])
                    carry[vn]["block_table"] = bt_eff
                    carry[vn]["cache_pos"] = pos0
                out, nc = fwd(params, state, onehot, carry, mask)
                new_pool = {vn: {k: nc[vn][k] for k in pool[vn]}
                            for vn in paged}
                rows = jnp.take_along_axis(
                    out, (sufflen - 1)[:, None, None], axis=1)[:, 0]
                k0 = jax.vmap(jax.random.fold_in)(
                    base_keys, jnp.zeros_like(sufflen))
                first = sampled_next_token(rows, k0, temp, topk)
                return new_pool, first

            return jax.jit(prefill, donate_argnums=(2,))

        return self._get_program(net, key, build)

    def _page_copy_program(self):
        """Copy-on-write: duplicate one pool page (all layers) into a
        fresh page. Traced page ids — compiled once."""
        import jax

        paged = tuple(self._paged_names)
        key = ("gen_page_copy", self._mesh)

        def build():
            def copy(pool, src, dst):
                # generic per-leaf copy: int8 pools also carry scale
                # planes, and COW must duplicate them with the values
                return {vn: {k: a.at[dst].set(a[src])
                             for k, a in pool[vn].items()}
                        for vn in paged}

            return jax.jit(copy, donate_argnums=(0,))

        return self._get_program(self.net, key, build)

    def _page_fetch_program(self):
        """Snapshot export: gather a block-table-width stack of pool
        pages (all layers, scale planes included) in one dispatch. NOT
        donating — the pool stays live; page ids are traced data, so
        every export replays this one program."""
        import jax

        paged = tuple(self._paged_names)
        key = ("gen_page_fetch", self._mesh)

        def build():
            def fetch(pool, idx):
                return {vn: {k: a[idx] for k, a in pool[vn].items()}
                        for vn in paged}

            return jax.jit(fetch)

        return self._get_program(self.net, key, build)

    def _page_store_program(self):
        """Snapshot adopt: scatter a block-table-width stack of page
        payloads into pool rows ``dst`` (all layers, scale planes
        included). Rows the adopter does not need (padding, or pages
        deduped against the prefix cache) are routed to the garbage
        page. Donating in-place, rebound by the caller — compiled
        once."""
        import jax

        paged = tuple(self._paged_names)
        key = ("gen_page_store", self._mesh)

        def build():
            def store(pool, dst, data):
                return {vn: {k: a.at[dst].set(data[vn][k])
                             for k, a in pool[vn].items()}
                        for vn in paged}

            return jax.jit(store, donate_argnums=(0,))

        return self._get_program(self.net, key, build)

    def _draft_prefill_program(self, bucket: int):
        """Draft-side prefill for one pow2 token bucket: consume the full
        (padded, masked) prompt with a fresh batch-1 dense carry and
        scatter the filled caches into draft pool row ``slot``. No
        sampling — the draft only needs its cache primed."""
        import jax
        import jax.numpy as jnp

        from deeplearning4j_tpu.models.zoo import lm_stream_forward

        draft = self._draft
        d_attn = tuple(self._d_attn_names)
        d_pos = tuple(self._d_pos_names)
        key = ("gen_draft_prefill", self.slots, self.vocab, bucket)

        def build():
            dfwd = lm_stream_forward(draft)

            def dprefill(dparams, dstate, dpool, slot, onehot, mask):
                one = {}
                for vn in d_pos:
                    one[vn] = {"cache_pos": jnp.zeros((), jnp.int32)}
                for vn in d_attn:
                    kc = dpool[vn]["kcache"]
                    one[vn] = {
                        "kcache": jnp.zeros((1,) + kc.shape[1:], kc.dtype),
                        "vcache": jnp.zeros((1,) + kc.shape[1:], kc.dtype),
                        "cache_pos": jnp.zeros((), jnp.int32)}
                _, c1 = dfwd(dparams, dstate, onehot, one, mask)
                return {vn: {
                    "kcache": dpool[vn]["kcache"].at[slot].set(
                        c1[vn]["kcache"][0]),
                    "vcache": dpool[vn]["vcache"].at[slot].set(
                        c1[vn]["vcache"][0])} for vn in d_attn}

            return jax.jit(dprefill, donate_argnums=(2,))

        return draft._get_output(key, build)

    def _spec_program(self):
        """One speculative round, fused: the draft scans K-1 proposal
        steps over its dense cache (same fold_in key schedule the target
        would use for those token indices), then the target verifies all
        K positions in ONE chunked paged forward. Returns the target's
        selections [S, K] and the per-slot count of leading draft
        matches — everything the host needs to emit min(acc+1, K)
        tokens, every one of them a TARGET selection under the serial
        schedule (bit-exactness by construction)."""
        import jax
        import jax.numpy as jnp

        from deeplearning4j_tpu.models.zoo import (lm_stream_forward,
                                                   sampled_next_token,
                                                   spec_verify_tokens)

        net, draft, vocab = self.net, self._draft, self.vocab
        k_spec = self.spec_k
        paged = tuple(self._paged_names)
        pos_only = tuple(self._pos_names)
        d_attn = tuple(self._d_attn_names)
        d_pos = tuple(self._d_pos_names)
        # the closure captures BOTH nets, so the program lives in the
        # DRAFT's cache (it dies with the draft) keyed by the target's
        # identity — a draft shared across servers never replays a
        # program traced against a different target
        key = ("gen_spec", id(net), self.slots, vocab, k_spec,
               self.kv_dtype, self._mesh, self._pa)

        def build():
            fwd = lm_stream_forward(net)
            dfwd = lm_stream_forward(draft)
            dtype = jnp.dtype(net.conf.dtype)

            def dcarry(dp, pos):
                carry = {}
                for vn in d_pos:
                    carry[vn] = {"cache_pos": pos}
                for vn in d_attn:
                    carry[vn] = {"kcache": dp[vn]["kcache"],
                                 "vcache": dp[vn]["vcache"],
                                 "cache_pos": pos}
                return carry

            def strip_d(nc):
                return {vn: {"kcache": nc[vn]["kcache"],
                             "vcache": nc[vn]["vcache"]} for vn in d_attn}

            def spec(params, state, dparams, dstate, pool, dpool, bt,
                     positions, last, active, temp, topk, base_keys,
                     counts):
                def body(cs, _):
                    dp, pos, cur, cnt = cs
                    x = jax.nn.one_hot(cur, vocab, dtype=dtype)[:, None, :]
                    out, nc = dfwd(dparams, dstate, x, dcarry(dp, pos))
                    keys = jax.vmap(jax.random.fold_in)(base_keys, cnt)
                    prop = sampled_next_token(out[:, 0], keys, temp, topk)
                    prop = jnp.where(active, prop, cur).astype(cur.dtype)
                    return (strip_d(nc), jnp.where(active, pos + 1, pos),
                            prop, jnp.where(active, cnt + 1, cnt)), prop

                (dpool, pos_f, cur_f, _), props = jax.lax.scan(
                    body, (dpool, positions, last, counts), None,
                    length=k_spec - 1)
                # feed the last proposal too (output unused): a
                # full-accept round then leaves the draft cache
                # hole-free at position pos + K - 1
                x = jax.nn.one_hot(cur_f, vocab, dtype=dtype)[:, None, :]
                _, nc = dfwd(dparams, dstate, x, dcarry(dpool, pos_f))
                dpool = strip_d(nc)

                drafts = props.T                         # [S, K-1]
                chunk = jnp.concatenate([last[:, None], drafts], axis=1)
                x = jax.nn.one_hot(chunk, vocab, dtype=dtype)  # [S, K, V]
                carry = {}
                for vn in pos_only:
                    carry[vn] = {"cache_pos": positions}
                for vn in paged:
                    # generic over kv dtypes (int8 pools add scale planes)
                    carry[vn] = dict(pool[vn])
                    carry[vn]["block_table"] = bt
                    carry[vn]["cache_pos"] = positions
                out, nc = fwd(params, state, x, carry)   # [S, K, V]
                new_pool = {vn: {k: nc[vn][k] for k in pool[vn]}
                            for vn in paged}
                true = spec_verify_tokens(out, base_keys, counts, temp,
                                          topk)          # [S, K]
                match = (drafts == true[:, :k_spec - 1]).astype(jnp.int32)
                acc = jnp.sum(jnp.cumprod(match, axis=1), axis=1)
                return new_pool, dpool, true, acc

            return jax.jit(spec, donate_argnums=(4, 5))

        return self._get_program(draft, key, build)

    # ------------------------------------------------------------- submit
    def submit(self, prompt_ids, max_tokens: int, *,
               temperature: float = 0.0, top_k: int = 0, seed: int = 0,
               eos_id=_UNSET, deadline_s: Optional[float] = None,
               export_kv: Optional[bool] = None) -> Future:
        """Queue one generation request; returns a Future resolving to
        the generated ids ([<= max_tokens] numpy int array — shorter when
        the per-request ``eos_id`` / server default is produced, which is
        included). Raises a typed ``ServerOverloaded`` when the request
        cannot fit the page budget (up front — never mid-prefill after a
        slot is consumed) or past the admission watermark, and
        ``CircuitOpen`` while dispatches are failing.

        ``export_kv`` selects the disaggregated-prefill outcome: True
        resolves the future to a ``KVSnapshot`` right after prefill
        (first token included in its header) for a decode-tier server
        to adopt; False decodes to completion here. The default (None)
        follows the server ``role`` — True on a prefill-role server,
        False otherwise — so a degraded fleet can co-locate decode on
        the prefill tier by passing ``export_kv=False`` explicitly."""
        prompt = np.asarray(prompt_ids)
        if prompt.ndim != 1 or prompt.shape[0] < 1:
            raise ValueError(f"prompt_ids must be a non-empty 1-D id "
                             f"array, got shape {prompt.shape}")
        if max_tokens < 1:
            raise ValueError(f"max_tokens must be >= 1, got {max_tokens}")
        if temperature < 0:
            raise ValueError(f"temperature must be >= 0, got {temperature}")
        if top_k < 0 or top_k > self.vocab:
            raise ValueError(f"top_k must be in [0, {self.vocab}], "
                             f"got {top_k}")
        plen = int(prompt.shape[0])
        # page-budget feasibility, up front: prompt + generated positions
        # (+ the speculative look-ahead margin a verify chunk writes —
        # the plain decode dispatch write-clamps at capacity, so it
        # needs none) must fit the block table AND the pool with the
        # garbage page excluded; prefill padding writes the garbage
        # page, so buckets add no transient page pressure
        margin = self.spec_k - 1 if self._draft is not None else 0
        need_tokens = plen + int(max_tokens) + margin - 1
        if need_tokens > self._cap_tokens:
            raise ServerOverloaded(
                f"infeasible request: prompt {plen} + max_tokens "
                f"{max_tokens} (+{margin} look-ahead) exceeds the per-"
                f"slot KV capacity {self._cap_tokens} "
                f"({self._np} pages x {self._ps})")
        need_pages = -(-need_tokens // self._ps)
        if need_pages > self.pages_total - 1:
            raise ServerOverloaded(
                f"infeasible request: needs {need_pages} pages but the "
                f"pool capacity is {self.pages_total - 1} usable pages "
                f"of {self._ps} tokens")
        with self._cond:
            if self._closing:
                raise RuntimeError("GenerationServer is closed")
        if not self.breaker.allow():
            raise CircuitOpen("circuit breaker is open: recent decode "
                              "dispatches failed above threshold")
        budget = deadline_s if deadline_s is not None \
            else self.request_deadline_s
        req = _Request(prompt.astype(np.int64), int(max_tokens),
                       float(temperature), int(top_k), int(seed),
                       self.eos_id if eos_id is _UNSET else eos_id,
                       None if budget is None else Deadline(budget))
        req.export_kv = (self.role == "prefill") if export_kv is None \
            else bool(export_kv)
        if req.export_kv and self._draft is not None:
            raise SnapshotUnsupported(
                "speculative servers cannot export: the draft's dense "
                "KV cache is not part of the KVSnapshot wire format")
        # export_request / the fleet clamp their waits to the request's
        # own remaining budget through this stamp
        req.future._deadline = req.deadline
        self.admission.acquire()  # raises ServerOverloaded at watermark
        req.future.add_done_callback(lambda _f: self.admission.release())
        with self._cond:
            if self._closing:
                # lost the race with close(): fail typed, not hung
                self._fail(req, RuntimeError("GenerationServer is closed"))
                return req.future
            self._queue.append(req)
            self._cond.notify_all()
        return req.future

    # ---------------------------------------------------------- the loop
    def _wake_loop(self):
        """Runtime wake hook: nudge a tick blocked on ``_cond``."""
        with self._cond:
            self._cond.notify_all()

    def _tick_once(self) -> bool:
        """One scheduling round of the decode loop, hosted by the
        ``ServingLoop`` tick thread ("generation-server"). Returns False
        only on a clean stop (loop CLOSED)."""
        with self._cond:
            if self._stop:
                return False
            migrating = self._migrating
            if (not self._queue and self._n_active == 0
                    and not self._export_q and not migrating):
                self._cond.wait(timeout=0.5)
                return True
        try:
            if migrating:
                if self._chaos is not None:
                    # a migrate-out sweep IS a drain phase: shutdown-phase
                    # chaos (kill_during_drain) attacks it too, and the
                    # LoopKilled it raises is a BaseException precisely so
                    # it escapes the except below into the supervisor
                    fault = getattr(self._chaos, "drain_fault", None)
                    if fault is not None:
                        fault()
                self._migrate_out()
            self._admit_free_slots()
            with self._cond:
                n_active = self._n_active
            if n_active:
                t0 = time.monotonic()
                if self._draft is not None:
                    self._spec_decode_once()
                elif self._mesh is not None:
                    self._mesh_decode_once()
                else:
                    self._decode_once()
                self._m_busy_s.inc(time.monotonic() - t0)
            self._expire_active()
            # handoff housekeeping rides BETWEEN dispatches: explicit
            # exports first (a caller is blocked on them), then at
            # most one periodic low-priority snapshot per iteration
            self._service_exports()
            self._maybe_snapshot_slots()
        except Exception as e:  # noqa: BLE001 — a loop death would
            # hang every outstanding future; fail them typed instead
            self._fail_all(e)
        return True

    def _on_loop_death(self, loop, exc) -> bool:
        """Supervisor recovery hook: the decode tick thread died (a chaos
        kill or an untrappable fault that escaped ``_fail_all``). Fail
        every in-flight future and pending export typed, release the dead
        slots' pages, and — unless the server was deliberately closed —
        rebuild device state so the supervised restart serves cleanly."""
        err = LoopCrashed("generation-server loop died with the request "
                          f"in flight: {exc!r}")
        with self._cond:
            stragglers = [s for s in range(self.slots)
                          if self._slot_req[s] is not None]
            victims = [self._slot_req[s] for s in stragglers]
            victims += list(self._queue)
            self._queue.clear()
            self._slot_req = [None] * self.slots
            self._n_active = 0
            exports = list(self._export_q)
            self._export_q.clear()
            # a kill mid-migration resolved every live future (below), so
            # the migration is over — a latched flag would make the
            # restarted tick re-enter the drain path forever
            self._migrating = False
            self._migrate_cb = None
            again = not self._user_close
            self._cond.notify_all()
        self._m_failed.inc(len(victims))
        for req in victims:
            self._fail(req, err)
        for _fut, out in exports:  # never leave an exporter hung
            self._fail_export(out, SnapshotUnavailable(
                "generation loop died before the export was serviced"))
        for s in stragglers:  # tick thread is dead: safe to touch pages
            self._release_slot_pages(s)
        if again:
            self._m_pool_rebuilds.inc()
            self._reset_device_state()
        return again

    def _pop_admittable(self):
        """Next queued request still worth prefilling (expired ones fail
        typed on the way — counted and resolved OUTSIDE ``_cond``)."""
        while True:
            with self._cond:
                if not self._queue:
                    return None
                req = self._queue.popleft()
            if req.deadline is not None and req.deadline.expired():
                self._m_expired.inc()
                self._fail(req, DeadlineExceeded(
                    "request budget exhausted while queued "
                    f"({-req.deadline.remaining() * 1e3:.1f} ms over)"))
                continue
            return req

    def _admit_free_slots(self):
        """Admit every queued request a free slot and the page pool can
        take, then prefill the whole wave together, one batched dispatch
        per chunk round (Orca-style iteration-level scheduling: weights
        are read once per round, not once per request)."""
        staged = []                          # (slot, req, pos0, plen, t0)
        with self._cond:
            # the autoscaler's admission cap bounds occupancy, not the
            # pool: slots past the cap stay empty until it rises again
            budget = self._active_cap - self._n_active
        for s in range(self.slots):
            if len(staged) >= budget:
                break
            if self._slot_req[s] is not None:
                continue
            req = self._pop_admittable()
            if req is None:
                break
            t0 = time.monotonic()
            if req.snapshot is not None and self._adopt_into_slot(
                    s, req, t0):
                continue
            # no snapshot (or adoption fell back): token-0 prefill
            plen = req.prompt.shape[0]
            try:
                pos0 = self._stage_prompt_pages(s, req.prompt, plen)
            except RuntimeError as e:  # pool exhausted during staging
                self._release_slot_pages(s)
                if staged:
                    # transient pressure from this same admission wave:
                    # requeue and batch what already staged — their
                    # completions free the pages this request needs
                    with self._cond:
                        self._queue.appendleft(req)
                    break
                self._m_failed.inc()
                self._fail(req, e)
                continue
            except Exception as e:  # noqa: BLE001 — typed failure for
                # this request only; the slot stays free for the next one
                self._release_slot_pages(s)
                if isinstance(e, DeadlineExceeded):
                    self._m_expired.inc()
                else:
                    self._m_failed.inc()
                self._fail(req, e)
                continue
            staged.append((s, req, pos0, plen, t0))
        if staged:
            self._prefill_wave(staged)

    # -------------------------------------------------- page bookkeeping
    def _release_slot_pages(self, slot: int):
        sp = self._slot_pages[slot]
        for page in sp:
            self._page_pool.release(page)
        sp.clear()
        self._bt[slot, :] = 0
        self._pos[slot] = 0

    def _pick_victim(self, keep_slot: int):
        best, best_seq = None, -1
        for s in range(self.slots):
            if s == keep_slot or self._slot_req[s] is None:
                continue
            if self._slot_seq[s] > best_seq:
                best, best_seq = s, self._slot_seq[s]
        return best

    def _preempt(self, slot: int):
        """Free the most recently admitted slot's pages under pool
        pressure: its request is requeued at the FRONT. A victim with at
        least a page's worth of decoded state snapshots BEFORE its pages
        are freed, so re-admission ADOPTS the snapshot and resumes at
        position N instead of recomputing the prefix (the deterministic
        key schedule makes either path bit-identical, so preemption is
        invisible in outputs — the snapshot only saves the recompute)."""
        req = self._slot_req[slot]
        if (req.snapshot is None and self._draft is None
                and len(req.tokens) >= self._ps):
            try:
                snap = self._snapshot_slot(slot)
            except Exception:  # noqa: BLE001 — best-effort: a failed
                # snapshot degrades to the legacy recompute, never fails
                # the request
                snap = None
            if snap is not None:
                req.snapshot = snap
                self._m_preempt_resumes.inc()
        if req.snapshot is None:
            req.tokens.clear()
        self._release_slot_pages(slot)
        self._m_preempted.inc()
        with self._cond:
            self._slot_req[slot] = None
            self._n_active -= 1
            self._queue.appendleft(req)
            self._cond.notify_all()

    def _alloc_page(self, for_slot: int) -> int:
        while True:
            page = self._page_pool.alloc()
            if page is not None:
                return page
            victim = self._pick_victim(for_slot)
            if victim is None:
                raise RuntimeError(
                    "page pool exhausted with nothing left to preempt — "
                    "admission should have rejected this request")
            self._preempt(victim)

    def _ensure_writable(self, slot: int, idx: int):
        """Copy-on-write: the slot is about to write into its idx-th
        logical page; if that page is shared (or pinned pristine by the
        prefix cache) copy it off and repoint the block table."""
        sp = self._slot_pages[slot]
        page = sp[idx]
        if not self._page_pool.protected(page):
            return
        dst = self._alloc_page(slot)
        prog = self._page_copy_program()
        self._pool = prog(self._pool, np.int32(page), np.int32(dst))
        self._m_cow_copies.inc()
        self._page_pool.release(page)
        sp[idx] = dst
        self._bt[slot, idx] = dst

    def _ensure_slot_pages(self, slot: int, upto: int, write_from: int):
        """Slot ``slot`` is about to write positions
        [write_from, upto): allocate any missing pages and COW the
        shared ones in the write range."""
        sp = self._slot_pages[slot]
        n = -(-upto // self._ps)
        if n > self._np:
            raise RuntimeError(
                f"slot {slot} needs {n} pages > block table width "
                f"{self._np} — admission should have rejected this")
        while len(sp) < n:
            page = self._alloc_page(slot)
            self._bt[slot, len(sp)] = page
            sp.append(page)
        for idx in range(write_from // self._ps,
                         (upto - 1) // self._ps + 1):
            self._ensure_writable(slot, idx)

    def _reserve_decode_pages(self):
        """Page capacity for one decode dispatch: every active slot gets
        pages covering its next ``lookahead`` writes (alloc + COW),
        preempting the newest slots under pressure."""
        look = self._lookahead
        for s in range(self.slots):
            if self._slot_req[s] is None:
                continue
            pos = int(self._pos[s])
            # the dispatch write-clamps at capacity, so pages past the
            # per-slot cap are never touched (overshoot lands on the
            # garbage page)
            upto = min(pos + look, self._cap_tokens)
            if upto > pos:
                self._ensure_slot_pages(s, upto, write_from=pos)

    def _prefix_digest(self, digest: bytes, chunk) -> bytes:
        return hashlib.sha1(digest + chunk.tobytes()).digest()

    def _match_prefix(self, prompt, plen: int):
        """Longest shared prefix already resident: full page-aligned
        chunks under the chained digest, then the exact whole-prompt
        tail. Returns (shared page list, matched token count) with the
        shares already refcounted; at least one suffix token is always
        left to prefill (the sampled first token needs a true
        position)."""
        if not self.prefix_cache:
            return [], 0
        pool = self._page_pool
        ps = self._ps
        digest = b""
        pages: list = []
        matched = 0
        full = plen // ps
        for i in range(full):
            digest = self._prefix_digest(digest, prompt[i * ps:(i + 1) * ps])
            page = pool.lookup(digest)
            if page is None:
                break
            pages.append(page)
            matched += ps
        else:
            rem = prompt[full * ps:]
            if rem.size:
                tkey = hashlib.sha1(digest + b"T" + rem.tobytes()).digest()
                page = pool.lookup(tkey)
                if page is not None:
                    pages.append(page)
                    matched = plen
        if matched >= plen:
            # whole prompt resident: un-share the final token — its
            # 1-token suffix prefill writes into the shared page, which
            # COWs off the slot's private copy (the genuine COW trigger)
            matched = plen - 1
        for page in pages:
            pool.share(page)
        return pages, matched

    def _stage_prompt_pages(self, slot: int, prompt, plen: int):
        """Assemble the slot's block-table row for prefill: adopt shared
        prefix pages, then allocate private pages for the true suffix
        tokens only — bucket padding inside a prefill round writes the
        garbage page, so it needs no backing. Returns the suffix
        offset."""
        shared, matched = self._match_prefix(prompt, plen)
        sp = self._slot_pages[slot]
        sp.extend(shared)
        for i, page in enumerate(shared):
            self._bt[slot, i] = page
        if matched:
            self._m_prefix_hits.inc()
            self._m_prefix_reused.inc(matched)
        self._ensure_slot_pages(slot, plen, write_from=matched)
        return matched

    def _trim_slot_pages(self, slot: int, plen: int):
        """Drop prefill bucket over-allocation: pages wholly beyond the
        next write position hold only padding garbage — return them to
        the pool; decode re-allocates on demand."""
        sp = self._slot_pages[slot]
        keep = plen // self._ps + 1
        while len(sp) > keep:
            page = sp.pop()
            self._bt[slot, len(sp)] = 0
            self._page_pool.release(page)

    def _register_prefix(self, slot: int, prompt, plen: int):
        """Publish the slot's prompt pages in the prefix cache: full
        page-aligned chunks under the chained digest, plus the whole-
        prompt partial tail. Registered pages become copy-protected —
        the first divergent write (this slot's own next decode token
        included) COWs off a private copy, leaving the cached original
        pristine for future sharers."""
        if not self.prefix_cache:
            return
        sp = self._slot_pages[slot]
        pool = self._page_pool
        ps = self._ps
        digest = b""
        full = plen // ps
        for i in range(full):
            digest = self._prefix_digest(digest, prompt[i * ps:(i + 1) * ps])
            pool.register(digest, sp[i])
        rem = prompt[full * ps:]
        if rem.size and full < len(sp):
            tkey = hashlib.sha1(digest + b"T" + rem.tobytes()).digest()
            pool.register(tkey, sp[full])

    # ------------------------------------------------------ prefill path
    def _prefill_wave(self, group):
        """Batched chunked prefill for one admission wave: every staged
        slot advances through rounds of at most ``prefill_chunk`` suffix
        tokens, ONE dispatch per round for the rows with suffix left
        (Sarathi-style chunked prefill — the transient per-round
        activations stay bounded no matter how long the prompts are,
        while weights are still read once per round for the whole wave).
        Chunk boundaries are numerically transparent: each token's
        attention reduces over exactly the columns at or before its true
        position in the same order, so outputs are bit-identical to a
        single full-length prefill. A row samples its first token in
        the round consuming its final chunk; a dispatch failure fails
        the whole wave typed (pages released, slots stay free)."""
        import jax

        dtype = np.dtype(self.net.conf.dtype)
        S = self.slots
        keys = np.zeros((S, 2), np.uint32)
        cur = {}
        first = {}
        deadline = None
        for s, req, pos0, _, _ in group:
            cur[s] = pos0
            keys[s] = jax.device_get(jax.random.PRNGKey(req.seed))
            if req.deadline is not None and (
                    deadline is None or req.deadline.remaining()
                    < deadline.remaining()):
                deadline = req.deadline
        cap_pages = max(1, self._chunk_cap // self._ps)
        while True:
            live = [(s, req, plen) for s, req, _, plen, _ in group
                    if cur[s] < plen]
            if not live:
                break
            chunk = {s: min(plen - cur[s], self._chunk_cap)
                     for s, _, plen in live}
            target = max(max(chunk.values()), self.min_prefill_bucket)
            bucket = bucket_pages(target, self._ps,
                                  maximum=min(self._np, cap_pages)) * self._ps
            prog = self._prefill_program(bucket)
            onehot = np.zeros((S, bucket, self.vocab), dtype)
            mask = np.zeros((S, bucket), np.float32)
            admit = np.zeros((S,), bool)
            positions = np.zeros((S,), np.int32)
            sufflen = np.ones((S,), np.int32)
            temp = np.zeros((S,), np.float32)
            topk = np.zeros((S,), np.int32)
            for s, req, _ in live:
                n = chunk[s]
                onehot[s, np.arange(n), req.prompt[cur[s]:cur[s] + n]] = 1
                mask[s, :n] = 1
                admit[s] = True
                positions[s] = cur[s]
                sufflen[s] = n
                temp[s] = req.temperature
                topk[s] = req.top_k
            dispatch = prog if self._chaos is None \
                else self._chaos.wrap(prog)

            def attempt():
                try:
                    out = dispatch(self.net.params, self.net.state,
                                   self._pool, self._bt, positions, onehot,
                                   mask, sufflen, temp, topk, keys, admit)
                except Exception:
                    self.breaker.record_failure()
                    raise
                self.breaker.record_success()
                return out

            try:
                new_pool, sampled = self.retry.call(
                    attempt, deadline=deadline, on_retry=self._count_retry)
            except Exception as e:  # noqa: BLE001 — typed failure for the
                # wave; every staged slot stays free for the next one
                for s, req, *_ in group:
                    self._release_slot_pages(s)
                    if isinstance(e, DeadlineExceeded):
                        self._m_expired.inc()
                    else:
                        self._m_failed.inc()
                    self._fail(req, e)
                return
            self._pool = new_pool
            toks = jax.device_get(sampled).tolist()  # ONE fetch per round
            for s, _, plen in live:
                cur[s] += chunk[s]
                if cur[s] >= plen:
                    # this round consumed the row's final chunk, so its
                    # sampled token came from the true last position;
                    # earlier rounds' samples are padding garbage
                    first[s] = toks[s]
        for s, req, pos0, plen, t0 in group:
            if self._draft is not None:
                try:
                    self._draft_prefill(s, req, plen)
                except Exception as e:  # noqa: BLE001
                    self._release_slot_pages(s)
                    if isinstance(e, DeadlineExceeded):
                        self._m_expired.inc()
                    else:
                        self._m_failed.inc()
                    self._fail(req, e)
                    continue
            self._commit_slot(s, req, plen, first[s], keys[s], t0)
        # disaggregated prefill: export the wave's export_kv slots that
        # are still live (a request that finished on its first token was
        # already retired with a complete result — no handoff needed)
        exports = [(s, req) for s, req, *_ in group
                   if req.export_kv and self._slot_req[s] is req]
        if exports:
            self._transfer_loop(exports)

    def _commit_slot(self, slot: int, req: _Request, plen: int, tok,
                     key, t0: float):
        """Publish one prefilled slot: trim the bucket over-allocation,
        register its prefix pages, seed the decode mirrors, and mark the
        slot active."""
        self._trim_slot_pages(slot, plen)
        self._register_prefix(slot, req.prompt, plen)
        self._last[slot] = tok
        self._counts[slot] = 1
        self._snap_counts[slot] = 0  # fresh stream: restart the cadence
        self._temp[slot] = req.temperature
        self._topk[slot] = req.top_k
        self._keys[slot] = key
        self._pos[slot] = plen
        req.tokens.append(tok)
        # TTFT stamp: the first token exists NOW, even when the request
        # later crosses the tier boundary (fleet histograms read this)
        req.future._t_first = time.monotonic()
        self._admit_seq += 1
        self._slot_seq[slot] = self._admit_seq
        with self._cond:
            self._slot_req[slot] = req
            self._n_active += 1
        self._m_busy_s.inc(time.monotonic() - t0)
        self._m_prefills.inc()
        self._m_admitted.inc()
        self._m_tokens.inc()
        if self._finished(req, tok):
            self._retire(slot, req)

    def _draft_prefill(self, slot: int, req: _Request, plen: int):
        """Prime the draft's dense cache row for ``slot`` with the full
        prompt (the dense draft cache cannot share pages)."""
        bucket = bucket_length(plen, minimum=self.min_prefill_bucket,
                               maximum=self._draft_cap)
        prog = self._draft_prefill_program(bucket)
        dtype = np.dtype(self._draft.conf.dtype)
        onehot = np.zeros((1, bucket, self.vocab), dtype)
        onehot[0, np.arange(plen), req.prompt] = 1
        mask = np.zeros((1, bucket), np.float32)
        mask[0, :plen] = 1
        dispatch = prog if self._chaos is None else self._chaos.wrap(prog)

        def attempt():
            try:
                out = dispatch(self._draft.params, self._draft.state,
                               self._dpool, np.int32(slot), onehot, mask)
            except Exception:
                self.breaker.record_failure()
                raise
            self.breaker.record_success()
            return out

        self._dpool = self.retry.call(attempt, deadline=req.deadline,
                                      on_retry=self._count_retry)

    # ------------------------------------------------------- decode path
    def _active_mask(self):
        return np.array([r is not None for r in self._slot_req])

    def _decode_once(self):
        import jax

        prog = self._decode_program()
        self._reserve_decode_pages()
        active = self._active_mask()
        dispatch = prog if self._chaos is None else self._chaos.wrap(prog)

        def attempt():
            try:
                out = dispatch(self.net.params, self.net.state, self._pool,
                               self._bt, self._pos, self._last, active,
                               self._temp, self._topk, self._keys,
                               self._counts)
            except Exception:
                self.breaker.record_failure()
                raise
            self.breaker.record_success()
            return out

        try:
            new_pool, seq = self.retry.call(attempt,
                                            on_retry=self._count_retry)
        except Exception as e:  # noqa: BLE001 — pool state is now
            # suspect (possibly donated away): fail the batch typed and
            # restart from a fresh pool so later requests still serve
            self._fail_all(e)
            return
        self._pool = new_pool
        toks = jax.device_get(seq)     # ONE [S, M] fetch per dispatch
        m_steps = self.steps_per_dispatch
        ntok = 0
        for s in range(self.slots):
            req = self._slot_req[s]
            if req is None:
                continue
            done = False
            for tok in toks[s].tolist():
                req.tokens.append(tok)
                ntok += 1
                if self._finished(req, tok):
                    done = True
                    break
            # the device advanced the full window regardless of where
            # the request finished; mirrors track the device (which
            # write-clamps position and count at capacity)
            adv = min(m_steps, self._cap_tokens - self._pos[s])
            self._counts[s] += adv
            self._pos[s] += adv
            self._last[s] = toks[s, m_steps - 1]
            if done:
                self._retire(s, req)
        # ONE registry publish per decode step, not one per token
        self._m_decode_steps.inc()
        self._m_tokens.inc(ntok)

    def _mesh_decode_once(self):
        """Mesh-path decode tick: ONE mesh-wide compiled dispatch
        advances every active slot ``steps_per_dispatch`` micro-steps
        over the head-sharded pool. The dispatch body is shared with
        ``_decode_once`` on purpose — the sharding is carried entirely
        by the pool's NamedSharding placement plus the layers' pushed
        ``paged_mesh`` (both baked into the mesh-keyed program), so one
        body means the mesh path can never drift from the bit-exact
        single-chip math, and occupancy churn stays data-only (zero
        retrace). On the graftcheck hot list like its single-chip twin:
        the one host sync is the batched ``[S, M]`` token fetch."""
        self._decode_once()

    def _spec_decode_once(self):
        import jax

        prog = self._spec_program()
        self._reserve_decode_pages()
        active = self._active_mask()
        dispatch = prog if self._chaos is None else self._chaos.wrap(prog)

        def attempt():
            try:
                out = dispatch(self.net.params, self.net.state,
                               self._draft.params, self._draft.state,
                               self._pool, self._dpool, self._bt,
                               self._pos, self._last, active, self._temp,
                               self._topk, self._keys, self._counts)
            except Exception:
                self.breaker.record_failure()
                raise
            self.breaker.record_success()
            return out

        try:
            new_pool, new_dpool, true, acc = self.retry.call(
                attempt, on_retry=self._count_retry)
        except Exception as e:  # noqa: BLE001 — both pools suspect
            self._fail_all(e)
            return
        self._pool = new_pool
        self._dpool = new_dpool
        true, acc = jax.device_get((true, acc))  # ONE fetch per round
        k_spec = self.spec_k
        ntok = 0
        proposed = 0
        accepted = 0
        for s in range(self.slots):
            req = self._slot_req[s]
            if req is None:
                continue
            n = min(acc[s] + 1, k_spec)
            proposed += k_spec - 1
            accepted += n - 1
            done = False
            for tok in true[s, :n].tolist():
                req.tokens.append(tok)
                ntok += 1
                if self._finished(req, tok):
                    done = True
                    break
            self._counts[s] += n
            self._pos[s] += n
            self._last[s] = true[s, n - 1]
            if done:
                self._retire(s, req)
        # ONE registry publish per speculative round, not one per slot
        self._m_spec_rounds.inc()
        self._m_spec_proposed.inc(proposed)
        self._m_spec_accepted.inc(accepted)
        self._m_decode_steps.inc()
        self._m_tokens.inc(ntok)

    def _finished(self, req: _Request, tok) -> bool:
        if req.eos_id is not None and tok == req.eos_id:
            return True
        return len(req.tokens) >= req.max_tokens

    def _retire(self, slot: int, req: _Request):
        self._release_slot_pages(slot)
        with self._cond:
            self._slot_req[slot] = None
            self._n_active -= 1
            self._cond.notify_all()
        self._m_retired.inc()
        self._m_completed.inc()
        try:
            req.future.set_result(np.asarray(req.tokens, np.int64))
        except Exception:  # future cancelled/resolved by the caller
            pass

    def _expire_active(self):
        for s in range(self.slots):
            req = self._slot_req[s]
            if req is None or req.deadline is None \
                    or not req.deadline.expired():
                continue
            self._release_slot_pages(s)
            with self._cond:
                self._slot_req[s] = None
                self._n_active -= 1
                self._cond.notify_all()
            self._m_expired.inc()
            self._fail(req, DeadlineExceeded(
                "request budget exhausted mid-generation after "
                f"{len(req.tokens)} tokens"))

    def _fail(self, req: _Request, exc: BaseException):
        try:
            req.future.set_exception(exc)
        except Exception:  # already resolved/cancelled
            pass

    def _fail_all(self, exc: BaseException):
        """Hard dispatch fault: every in-flight request fails typed
        (never hangs) and the page pool + device carries are rebuilt
        from zeros. The rebuild decision is taken under ``_cond`` so a
        chaos kill racing ``close()``/``drain()`` cannot resurrect device
        state on a server that is already shutting down — after the
        victims fail there is nothing left to serve, so a closing server
        skips the rebuild entirely (idempotent with close)."""
        with self._cond:
            victims = [r for r in self._slot_req if r is not None]
            victims += list(self._queue)
            self._queue.clear()
            self._slot_req = [None] * self.slots
            self._n_active = 0
            rebuild = not (self._closing or self._stop)
            self._cond.notify_all()
        self._m_failed.inc(len(victims))
        if rebuild:
            self._m_pool_rebuilds.inc()
        for req in victims:
            self._fail(req, exc)
        if rebuild:
            self._reset_device_state()

    def _reset_device_state(self):
        self._page_pool = _PagePool(self.pages_total)
        self._bt[:] = 0
        self._pos[:] = 0
        self._slot_pages = [[] for _ in range(self.slots)]
        self._pool = self._fresh_pool()
        if self._draft is not None:
            self._dpool = self._fresh_draft_pool()

    def _count_retry(self, attempt, exc):
        self._m_retried.inc()

    # ------------------------------------------------- snapshot/handoff
    def _snapshot_slot(self, slot: int) -> KVSnapshot:
        """Serialize slot ``slot``'s live state into a KVSnapshot: the
        pages holding KV positions [0, pos) — look-ahead pages beyond
        the stream position hold garbage and are skipped — fetched in
        ONE non-donating dispatch + ONE device_get, the prefix-cache
        digests of still-pristine chunk pages, and the resume header
        from the host mirrors. Loop-thread only; all host-side scalar
        conversion happens in ``pack_snapshot`` (this function is on the
        graftcheck hot list)."""
        import jax

        req = self._slot_req[slot]
        pos = self._pos[slot]
        n = -(-pos // self._ps)            # pages holding [0, pos)
        sp = self._slot_pages[slot]
        pool = self._page_pool
        digests = [pool.tag.get(p) for p in sp[:n]]
        idx = np.zeros(self._np, np.int32)  # pad rows fetch page 0
        idx[:n] = sp[:n]
        prog = self._page_fetch_program()
        # device_get of the (possibly head-sharded) gather assembles the
        # CANONICAL host layout — full [NP, H, ps, d] stacks — so the
        # wire payload is tp-independent and any-tp adopters re-shard
        # locally (_reshard_snapshot); the header records this server's
        # shard count for diagnostics only
        fetched = jax.device_get(prog(self._pool, idx))
        return pack_snapshot(
            req=req, pos=pos, count=self._counts[slot],
            last=self._last[slot], key=self._keys[slot].copy(),
            kv_dtype=self.kv_dtype, page_size=self._ps,
            page_token_bytes=self._page_token_bytes,
            page_digests=digests, fetched=fetched, n_pages=n,
            shards=self._tp, head_layout="canonical")

    def _publish_snapshot(self, req: _Request, snap: KVSnapshot):
        """Count the export, run the chaos injector, and attach the
        snapshot to the request's future — the transport: whoever holds
        the future (the fleet's done-callback, a migration driver) reads
        ``future._kv_snapshot`` when the request fails mid-stream. An
        injected ``drop`` makes the transfer vanish (nothing attached —
        the consumer falls back to whatever it already had); ``corrupt``
        and ``truncate`` damage the wire content so the adopter's
        checksum fails."""
        mode = None if self._chaos is None \
            else self._chaos.handoff_fault_mode()
        if mode == "drop":
            return
        if mode == "corrupt":
            corrupt_snapshot(snap)
        elif mode == "truncate":
            truncate_snapshot(snap)
        self._m_handoff_snapshots.inc()
        self._m_handoff_bytes.inc(snap.wire_bytes())
        req.future._kv_snapshot = snap

    def _transfer_loop(self, exports):
        """Disaggregated-prefill transfer: ship each freshly prefilled
        ``export_kv`` slot across the tier boundary — the future
        resolves to the ``KVSnapshot`` itself, the slot's pages free
        immediately (this is where the prefill tier's short slot
        residency comes from), and a decode-tier server adopts the
        snapshot to stream the rest. Failure never loses the request: a
        snapshot failure degrades to co-located decode in this server's
        own loop, and an injected transfer drop fails the future typed
        (``SnapshotUnavailable``, no snapshot attached) so a fleet
        re-prefills on a sibling. Loop-thread only; on the graftcheck
        hot list, so scalar host syncs stay in ``pack_snapshot``."""
        for slot, req in exports:
            if self._slot_req[slot] is not req:
                continue  # retired/expired between commit and transfer
            try:
                snap = self._snapshot_slot(slot)
            except Exception:  # noqa: BLE001 — degrade to co-located
                # decode: the slot stays active and this server streams
                # the completion itself (always correct, never lost)
                self._m_handoff_fallbacks.inc()
                continue
            mode = None if self._chaos is None \
                else self._chaos.handoff_fault_mode()
            if mode == "corrupt":
                corrupt_snapshot(snap)
            elif mode == "truncate":
                truncate_snapshot(snap)
            self._release_slot_pages(slot)
            with self._cond:
                self._slot_req[slot] = None
                self._n_active -= 1
                self._cond.notify_all()
            if mode == "drop":
                # the transfer vanished in flight: fail typed WITHOUT a
                # snapshot attached — the consumer re-runs the prefill
                # elsewhere (zero lost futures, some recompute)
                self._m_failed.inc()
                self._fail(req, SnapshotUnavailable(
                    "handoff transfer dropped in flight"))
                continue
            self._m_handoff_snapshots.inc()
            self._m_handoff_bytes.inc(snap.wire_bytes())
            self._m_prefill_exports.inc()
            self._m_retired.inc()
            self._m_completed.inc()
            try:
                req.future.set_result(snap)
            except Exception:  # caller gave up
                pass

    def _maybe_snapshot_slots(self):
        """Periodic low-priority snapshotting: at most ONE slot per loop
        iteration — the most overdue one — so exports never crowd out
        decode dispatches. Best-effort by design: a failed export leaves
        the slot exactly as it was (the fleet then falls back to token-0
        regeneration, which is always correct)."""
        if not self.snapshot_every:
            return
        best, best_lag = -1, 0
        for s in range(self.slots):
            if self._slot_req[s] is None:
                continue
            lag = int(self._counts[s]) - self._snap_counts[s]
            if lag >= self.snapshot_every and lag > best_lag:
                best, best_lag = s, lag
        if best < 0:
            return
        req = self._slot_req[best]
        try:
            snap = self._snapshot_slot(best)
        except Exception:  # noqa: BLE001 — best-effort
            return
        self._snap_counts[best] = int(self._counts[best])
        self._publish_snapshot(req, snap)

    def _service_exports(self):
        """Resolve queued ``export_request`` handshakes between
        dispatches (loop thread — the only thread allowed near the
        pool). Each resolves to a snapshot or fails typed; a request no
        longer resident in a slot is ``SnapshotUnavailable``."""
        while True:
            with self._cond:
                if not self._export_q:
                    return
                fut_in, out = self._export_q.popleft()
            slot = -1
            for s in range(self.slots):
                r = self._slot_req[s]
                if r is not None and r.future is fut_in:
                    slot = s
                    break
            if slot < 0:
                self._fail_export(out, SnapshotUnavailable(
                    "request is not resident in a decode slot (never "
                    "admitted, already retired, or failed)"))
                continue
            try:
                snap = self._snapshot_slot(slot)
            except Exception as e:  # noqa: BLE001 — typed to the caller
                self._fail_export(out, e)
                continue
            self._snap_counts[slot] = int(self._counts[slot])
            self._publish_snapshot(self._slot_req[slot], snap)
            try:
                out.set_result(snap)
            except Exception:  # caller gave up
                pass

    @staticmethod
    def _fail_export(out: Future, exc: BaseException):
        try:
            out.set_exception(exc)
        except Exception:  # caller gave up
            pass

    def export_request(self, future, timeout: Optional[float] = 30.0
                       ) -> KVSnapshot:
        """Snapshot the live request behind ``future`` (as returned by
        ``submit``). Blocks until the serving loop services the export
        between dispatches — never longer than the request's OWN
        remaining deadline budget: the wait is
        ``min(timeout, deadline.remaining())`` and expiry raises the
        typed ``DeadlineExceeded``, not a generic timeout. Raises
        ``SnapshotUnavailable`` when the request is not resident in a
        slot, ``SnapshotUnsupported`` on a speculative server."""
        if self._draft is not None:
            raise SnapshotUnsupported(
                "speculative servers cannot export: the draft's dense "
                "KV cache is not part of the KVSnapshot wire format")
        deadline = getattr(future, "_deadline", None)
        eff = timeout
        if deadline is not None:
            rem = deadline.remaining()
            if rem <= 0:
                raise DeadlineExceeded(
                    "request budget exhausted before the export "
                    f"({-rem * 1e3:.1f} ms over)")
            eff = rem if eff is None else min(eff, rem)
        out: Future = Future()
        with self._cond:
            if self._closing:
                raise RuntimeError("GenerationServer is closed")
            self._export_q.append((future, out))
            self._cond.notify_all()
        try:
            return out.result(timeout=eff)
        except FutureTimeout:
            if deadline is not None and deadline.expired():
                raise DeadlineExceeded(
                    "request budget exhausted waiting for the export "
                    f"({-deadline.remaining() * 1e3:.1f} ms over)")
            raise

    def adopt_request(self, snapshot: KVSnapshot, *,
                      deadline_s: Optional[float] = None) -> Future:
        """Rebuild a snapshotted request into this server and resume
        decoding at position N. Validation is all up front and typed:
        ``SnapshotInvalid`` (bad checksum/version/shape — the caller
        falls back to token-0 regeneration), ``SnapshotUnsupported``
        (kv_dtype/page-geometry mismatch or a speculative server),
        ``ServerOverloaded`` (cannot fit the page budget / admission
        watermark), ``CircuitOpen``. The resumed completion is
        byte-identical to the never-interrupted one: the serial
        ``fold_in(key, token_index)`` schedule rides in the snapshot."""
        if self._draft is not None:
            raise SnapshotUnsupported(
                "speculative servers cannot adopt: the draft's dense "
                "KV cache is not part of the KVSnapshot wire format")
        # v2 snapshots (single-chip geometry, no shard header) adopt as
        # the legacy fallback: their payload layout IS the canonical
        # shards=1 layout, so only the header generation differs
        if snapshot.version not in (WIRE_VERSION - 1, WIRE_VERSION):
            raise SnapshotInvalid(
                f"KVSnapshot wire version {snapshot.version} != "
                f"supported {WIRE_VERSION}")
        if not snapshot.verify():
            raise SnapshotInvalid("KVSnapshot checksum mismatch")
        if (snapshot.kv_dtype != self.kv_dtype
                or snapshot.page_size != self._ps
                or snapshot.page_token_bytes != self._page_token_bytes
                or snapshot.head_layout != "canonical"):
            raise SnapshotUnsupported(
                f"snapshot geometry (kv_dtype={snapshot.kv_dtype!r}, "
                f"page_size={snapshot.page_size}, "
                f"{snapshot.page_token_bytes} B/token, "
                f"head_layout={snapshot.head_layout!r}) does not match "
                f"this server (kv_dtype={self.kv_dtype!r}, "
                f"page_size={self._ps}, {self._page_token_bytes} "
                f"B/token, head_layout='canonical'); the exporter's "
                f"shard count ({snapshot.shards}) is free to differ — "
                "adopt re-shards to the local mesh")
        plen = int(snapshot.prompt.shape[0])
        if (snapshot.count != len(snapshot.tokens)
                or snapshot.pos != plen + snapshot.count - 1
                or snapshot.n_pages != -(-snapshot.pos // self._ps)):
            raise SnapshotInvalid(
                "inconsistent KVSnapshot header: position/count/page "
                "stack disagree with the token history")
        need_tokens = plen + snapshot.max_tokens - 1
        need_pages = -(-need_tokens // self._ps)
        if need_tokens > self._cap_tokens \
                or need_pages > self.pages_total - 1:
            raise ServerOverloaded(
                f"infeasible adoption: prompt {plen} + max_tokens "
                f"{snapshot.max_tokens} needs {need_pages} pages / "
                f"{need_tokens} tokens against capacity "
                f"{self.pages_total - 1} pages / {self._cap_tokens} "
                "tokens")
        if not self.breaker.allow():
            raise CircuitOpen("circuit breaker is open: recent decode "
                              "dispatches failed above threshold")
        # remaining-budget propagation across the tier boundary: an
        # explicit deadline_s wins, then the remaining budget the
        # snapshot carried from the exporting server (a duration — it
        # re-arms here against THIS host's monotonic clock), then this
        # server's default
        budget = deadline_s
        if budget is None:
            budget = snapshot.deadline_remaining
        if budget is None:
            budget = self.request_deadline_s
        req = _Request(snapshot.prompt.astype(np.int64),
                       snapshot.max_tokens, snapshot.temperature,
                       snapshot.top_k, snapshot.seed, snapshot.eos_id,
                       None if budget is None else Deadline(budget))
        req.tokens = list(snapshot.tokens)
        req.snapshot = snapshot
        req.future._deadline = req.deadline
        self.admission.acquire()  # raises ServerOverloaded at watermark
        req.future.add_done_callback(lambda _f: self.admission.release())
        with self._cond:
            if self._closing:
                self._fail(req, RuntimeError("GenerationServer is closed"))
                return req.future
            self._queue.append(req)
            self._cond.notify_all()
        return req.future

    def _adopt_into_slot(self, slot: int, req: _Request, t0: float) -> bool:
        """Rebuild ``req.snapshot`` into slot ``slot``: pages whose
        chunk digest is already resident are SHARED out of the prefix
        cache (no upload — shared prefixes re-dedupe on arrival), the
        rest are uploaded in ONE donated store dispatch, pristine prompt
        chunk pages are re-registered for future sharers, and the decode
        mirrors resume at position N. Returns False after rolling back
        (pool pressure) — the caller falls back to a token-0 prefill,
        which is always correct. Loop-thread only; on the graftcheck hot
        list, so scalar host syncs stay out of here."""
        snap = req.snapshot
        pool = self._page_pool
        sp = self._slot_pages[slot]
        n = snap.n_pages
        shared = set()
        try:
            for i in range(n):
                d = snap.page_digests[i]
                page = pool.lookup(d) \
                    if (d is not None and self.prefix_cache) else None
                if page is not None:
                    pool.share(page)
                    shared.add(i)
                else:
                    page = self._alloc_page(slot)
                self._bt[slot, i] = page
                sp.append(page)
        except RuntimeError:
            # pool exhausted mid-adoption: roll back and fall back to
            # the token-0 prefill path (fewer pages via prefix match,
            # and admission already proved the request itself feasible)
            self._release_slot_pages(slot)
            req.snapshot = None
            req.tokens.clear()
            self._m_handoff_fallbacks.inc()
            return False
        dst = np.zeros(self._np, np.int32)  # pad/dedup rows -> garbage
        for i in range(n):
            if i not in shared:
                dst[i] = self._bt[slot, i]
        prog = self._page_store_program()
        self._pool = prog(self._pool, dst, self._reshard_snapshot(
            padded_payload(snap, self._np)))
        # re-hash the pristine prompt chunk pages into this server's
        # prefix cache (the tail page already holds decoded tokens and
        # must NOT be registered under the whole-prompt tail key)
        plen = req.prompt.shape[0]
        if self.prefix_cache:
            digest = b""
            ps = self._ps
            for i in range(min(plen // ps, n)):
                digest = self._prefix_digest(
                    digest, req.prompt[i * ps:(i + 1) * ps])
                pool.register(digest, sp[i])
        self._last[slot] = snap.last
        self._counts[slot] = snap.count
        self._temp[slot] = req.temperature
        self._topk[slot] = req.top_k
        self._keys[slot] = snap.key
        self._pos[slot] = snap.pos
        self._admit_seq += 1
        self._slot_seq[slot] = self._admit_seq
        self._snap_counts[slot] = snap.count
        req.snapshot = None
        with self._cond:
            self._slot_req[slot] = req
            self._n_active += 1
        self._m_busy_s.inc(time.monotonic() - t0)
        self._m_admitted.inc()
        self._m_handoff_resumes.inc()
        self._m_handoff_saved.inc(len(req.tokens))
        if req.tokens and self._finished(req, req.tokens[-1]):
            self._retire(slot, req)
        return True

    def _migrate_out(self):
        """Drain-migrate sweep (loop thread): every live slot is
        snapshotted at its exact stream position and failed typed with
        ``RequestMigrated`` — the snapshot rides on the failed future,
        so a fleet (or any migration driver) adopts it elsewhere and
        loses zero tokens. Queued requests migrate with whatever
        snapshot they already carry (usually none: token-0 redispatch).
        A speculative server migrates snapshot-free — still zero lost
        futures, just recomputed."""
        with self._cond:
            cb = self._migrate_cb
            self._migrating = False
            self._migrate_cb = None
            queued = list(self._queue)
            self._queue.clear()
            self._cond.notify_all()
        for req in queued:
            if req.snapshot is not None:
                req.future._kv_snapshot = req.snapshot
            self._m_migrated.inc()
            self._fail(req, RequestMigrated(
                "request migrated off a draining server before prefill"))
        for s in range(self.slots):
            req = self._slot_req[s]
            if req is None:
                continue
            snap = None
            if self._draft is None:
                try:
                    snap = self._snapshot_slot(s)
                except Exception:  # noqa: BLE001 — degrade to token-0
                    snap = None
            if snap is not None:
                self._publish_snapshot(req, snap)
                if cb is not None:
                    try:
                        cb(snap)
                    except Exception:  # sink errors never lose requests
                        pass
            self._release_slot_pages(s)
            with self._cond:
                self._slot_req[s] = None
                self._n_active -= 1
                self._cond.notify_all()
            self._m_migrated.inc()
            self._fail(req, RequestMigrated(
                "request migrated off a draining server after "
                f"{len(req.tokens)} tokens"))

    # --------------------------------------------------------- lifecycle
    def drain(self, timeout: Optional[float] = None, *,
              migrate=False) -> bool:
        """Block until every queued and in-flight request has resolved
        (completed, expired, or failed). Returns False on timeout.

        ``migrate`` truthy flips the drain from wait-out to move-out:
        live requests are snapshotted and failed ``RequestMigrated``
        (snapshot attached to the failed future) instead of being
        decoded to completion — a fleet resumes them on another replica
        with zero recompute. Pass a callable to also receive each
        ``KVSnapshot`` as it is exported."""
        if migrate:
            with self._cond:
                self._migrating = True
                self._migrate_cb = migrate if callable(migrate) else None
                self._cond.notify_all()
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while self._queue or self._n_active:
                left = None if deadline is None \
                    else deadline - time.monotonic()
                if left is not None and left <= 0:
                    return False
                self._cond.wait(timeout=0.05 if left is None
                                else min(left, 0.05))
        return True

    def close(self, timeout: float = 30.0) -> None:
        """Stop admitting, drain what is in flight, stop the loop. Any
        request still unresolved past ``timeout`` fails typed — a closed
        server never leaves a hung future behind (and never leaks its
        pages). Idempotent and re-entrant: safe from any thread, twice,
        or concurrently — the runtime serializes the actual shutdown."""
        with self._cond:
            # before the drain begins, so a chaos kill landing mid-drain
            # cannot win a restart race against this deliberate close
            self._user_close = True
        self._runtime.begin_drain()   # submit() now rejects typed
        self.drain(timeout)
        self._runtime.close(max(timeout, 1.0))
        with self._cond:
            stragglers = [s for s in range(self.slots)
                          if self._slot_req[s] is not None]
            victims = [self._slot_req[s] for s in stragglers]
            victims += list(self._queue)
            self._queue.clear()
            self._slot_req = [None] * self.slots
            self._n_active = 0
            exports = list(self._export_q)
            self._export_q.clear()
        for _fut, out in exports:  # never leave an exporter hung
            self._fail_export(out, SnapshotUnavailable(
                "GenerationServer closed before the export was serviced"))
        for s in stragglers:   # loop thread is joined: safe to touch
            self._release_slot_pages(s)
        for req in victims:
            self._fail(req, RuntimeError("GenerationServer closed with "
                                         "the request still in flight"))
        # un-push the paged-attention override: layer config belongs to
        # the net, and the next server over this net must see the knob
        # it would have seen before this one existed
        for name, prev in self._pa_prev.items():
            self._layer_by_name[name].paged_attention = prev
        self._pa_prev = {}
        # same restore-on-close discipline for the mesh knob. The push
        # is build-scoped (see _get_program), so normally there is
        # nothing left to undo — this is the crash-safety net: if a
        # build died between push and restore, un-push OUR mesh (and
        # only ours — a sibling server's live Mesh is not ours to
        # touch) under the trace lock so no build is mid-flight.
        with GenerationServer._trace_lock:
            for name, prev in self._mesh_prev.items():
                layer = self._layer_by_name[name]
                if self._mesh is not None and layer.paged_mesh is self._mesh:
                    layer.paged_mesh = prev
        self._mesh_prev = {}

    # ------------------------------------------------------------- stats
    def stats(self) -> dict:
        """Serving counters: the observable surface for /stats, the
        bench, and ops. Counters come off the registry, so ``_cond`` is
        held only for the structural reads (occupancy and queue depth);
        the legacy key set and order are preserved byte-for-byte. The
        ``pages`` block carries the paged-KV gauges (pool occupancy,
        sharing, COW, speculative accept rate)."""
        with self._cond:
            n_active = self._n_active
            queued = len(self._queue)
        busy_s = self._m_busy_s.value
        tokens = int(self._m_tokens.value)
        out = {
            "slots": self.slots,
            "active_slots": n_active,
            "queued": queued,
            "admitted": int(self._m_admitted.value),
            "expired": int(self._m_expired.value),
            "retired": int(self._m_retired.value),
            "completed": int(self._m_completed.value),
            "failed": int(self._m_failed.value),
            "retried": int(self._m_retried.value),
            "pool_rebuilds": int(self._m_pool_rebuilds.value),
            "prefills": int(self._m_prefills.value),
            "decode_steps": int(self._m_decode_steps.value),
            "tokens_generated": tokens,
            "tokens_per_s": (tokens / busy_s if busy_s > 0 else 0.0),
        }
        out.update(accepted=self.admission.accepted,
                   rejected=self.admission.rejected,
                   pending=self.admission.pending,
                   breaker_state=self.breaker.state)
        # page/spec gauges are loop-thread-owned (read unlocked, like
        # _slot_req): a racy snapshot, never a torn structure
        pool = self._page_pool
        proposed = int(self._m_spec_proposed.value)
        accepted = int(self._m_spec_accepted.value)
        out["pages"] = {
            "page_size": self._ps,
            "pages_total": pool.total,
            "pages_free": len(pool.free),
            "pages_cached": len(pool.cache),
            "pages_shared": pool.shared_count(),
            "pages_refcounted": pool.refcounted(),
            "resident_kv_bytes": pool.in_use() * self._page_bytes,
            "peak_resident_kv_bytes": pool.peak * self._page_bytes,
            "cow_copies": int(self._m_cow_copies.value),
            "prefix_hits": int(self._m_prefix_hits.value),
            "prefix_tokens_reused": int(self._m_prefix_reused.value),
            "evictions": int(pool.evictions),
            "preempted": int(self._m_preempted.value),
            "spec_k": self.spec_k if self._draft is not None else 0,
            "spec_rounds": int(self._m_spec_rounds.value),
            "spec_proposed": proposed,
            "spec_accepted": accepted,
            "spec_accept_rate": (accepted / proposed) if proposed else 0.0,
            "kv_cache_dtype": self.kv_dtype or str(
                np.dtype(self.net.conf.dtype)),
            "bytes_per_token": self._page_token_bytes,
        }
        out["handoff"] = {
            "snapshot_every": self.snapshot_every,
            "snapshots": int(self._m_handoff_snapshots.value),
            "bytes": int(self._m_handoff_bytes.value),
            "resumes": int(self._m_handoff_resumes.value),
            "tokens_saved": int(self._m_handoff_saved.value),
            "fallbacks": int(self._m_handoff_fallbacks.value),
            "preempt_resumes": int(self._m_preempt_resumes.value),
            "migrated": int(self._m_migrated.value),
            "prefill_exports": int(self._m_prefill_exports.value),
        }
        out["role"] = self.role
        # the admission ledger must agree with the bytes XLA actually
        # allocated for the pool — satellite guard for the itemsize fix
        assert self._page_bytes_actual == self._page_bytes, (
            f"page accounting diverged: predicted {self._page_bytes} "
            f"bytes/page, allocated {self._page_bytes_actual}")
        return out
