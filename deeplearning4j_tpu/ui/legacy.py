"""Legacy visual iteration listeners, rebuilt on the declarative
components.

Reference: deeplearning4j-ui ui/weights/HistogramIterationListener.java
(per-iteration weight/gradient histograms + score to a web view),
ui/weights/ConvolutionalIterationListener.java (conv activation grids
rendered server-side to PNG), ui/flow/FlowIterationListener.java (model
topology + per-layer metadata view). TPU adaptation: each listener writes
a SELF-CONTAINED html report file every ``frequency`` iterations (a pod
worker has no Play server to talk to; a file per listener is scp-able and
diffable), rendered via ui/components.py. Activation grids become
ChartMatrix heatmaps of the feature maps computed from a user-supplied
probe batch.
"""

from __future__ import annotations

import os
from typing import Optional

import numpy as np

from deeplearning4j_tpu.optimize.listeners import TrainingListener
from deeplearning4j_tpu.ui.components import (
    ChartHistogram,
    ChartLine,
    ChartMatrix,
    ComponentTable,
    ComponentText,
    render_html_file,
)


def _histogram_chart(title: str, arr: np.ndarray,
                     bins: int = 30) -> ChartHistogram:
    counts, edges = np.histogram(np.asarray(arr).ravel(), bins=bins)
    h = ChartHistogram(title=title)
    for i, c in enumerate(counts):
        h.add_bin(edges[i], edges[i + 1], float(c))
    return h


class HistogramIterationListener(TrainingListener):
    """Score curve + per-parameter histograms every ``frequency``
    iterations (reference: HistogramIterationListener.java)."""

    def __init__(self, out_dir: str, frequency: int = 10,
                 filename: str = "histograms.html"):
        self.out_dir = out_dir
        self.frequency = max(1, frequency)
        self.filename = filename
        self._scores: list = []
        self._iters: list = []

    def iteration_done(self, model, iteration: int):
        self._iters.append(iteration)
        self._scores.append(float(model.score_value))
        if iteration % self.frequency != 0:
            return
        comps = [ComponentText(text=f"iteration {iteration}"),
                 ChartLine(title="score").add_series("score", self._iters,
                                                     self._scores)]
        for lk, lp in model.params.items():
            for pk, v in lp.items():
                comps.append(_histogram_chart(f"{lk}/{pk}", np.asarray(v)))
        os.makedirs(self.out_dir, exist_ok=True)
        render_html_file(comps, os.path.join(self.out_dir, self.filename),
                         title="histograms")


class FlowIterationListener(TrainingListener):
    """Model topology + per-layer parameter counts and score (reference:
    FlowIterationListener.java builds the flow view from model info)."""

    def __init__(self, out_dir: str, frequency: int = 10,
                 filename: str = "flow.html"):
        self.out_dir = out_dir
        self.frequency = max(1, frequency)
        self.filename = filename

    def iteration_done(self, model, iteration: int):
        if iteration % self.frequency != 0:
            return
        rows = []
        if hasattr(model, "layers"):  # MultiLayerNetwork
            it = ((str(i), layer) for i, layer in enumerate(model.layers))
        else:  # ComputationGraph
            it = ((name, v.layer) for name, v in model.conf.vertices.items()
                  if getattr(v, "layer", None) is not None)
        for key, layer in it:
            n = sum(int(np.asarray(p).size)
                    for p in model.params.get(key, {}).values())
            rows.append([key, type(layer).__name__, str(n)])
        comps = [
            ComponentText(text=f"{type(model).__name__} — iteration "
                               f"{iteration}, score "
                               f"{float(model.score_value):.6f}"),
            ComponentTable(header=["layer", "type", "params"], content=rows),
        ]
        os.makedirs(self.out_dir, exist_ok=True)
        render_html_file(comps, os.path.join(self.out_dir, self.filename),
                         title="flow")


class ConvolutionalIterationListener(TrainingListener):
    """Feature-map heatmaps of convolutional layers on a fixed probe input
    (reference: ConvolutionalIterationListener.java renders the same grids
    to PNG server-side). ``probe``: one input example [1, H, W, C] (NHWC);
    activations are recomputed at reporting iterations only."""

    def __init__(self, out_dir: str, probe, frequency: int = 10,
                 max_maps: int = 8, filename: str = "activations.html"):
        self.out_dir = out_dir
        self.probe = np.asarray(probe)
        self.frequency = max(1, frequency)
        self.max_maps = max_maps
        self.filename = filename

    def iteration_done(self, model, iteration: int):
        if iteration % self.frequency != 0:
            return
        if not hasattr(model, "feed_forward"):
            return
        acts = model.feed_forward(self.probe)
        # MultiLayerNetwork returns [input, act0, act1, ...];
        # ComputationGraph returns {vertex_name: activation}
        if isinstance(acts, dict):
            acts = list(acts.values())
        else:
            acts = acts[1:]
        comps = [ComponentText(text=f"activations at iteration "
                                    f"{iteration}")]
        for li, a in enumerate(acts):
            a = np.asarray(a)
            if a.ndim != 4:  # only conv-shaped [B, H, W, C]
                continue
            for ch in range(min(a.shape[-1], self.max_maps)):
                comps.append(ChartMatrix(
                    title=f"layer {li} map {ch}",
                    values=[[float(x) for x in row]
                            for row in a[0, :, :, ch]]))
        os.makedirs(self.out_dir, exist_ok=True)
        render_html_file(comps, os.path.join(self.out_dir, self.filename),
                         title="activations")
