"""UIServer: HTTP view over attached StatsStorage instances.

Reference: deeplearning4j-play PlayUIServer.java:53 (+ UIServer.java:24
singleton attach/detach) and the TrainModule overview route. Play+Scala
templates are replaced by Python's http.server with JSON endpoints and one
inline-JS overview page (no external dependencies):

- GET /train/sessions               -> session ids
- GET /train/overview?sid=...       -> score/time series + latest norms
- GET /train/model?sid=...          -> static model info
- GET /train/system?sid=...         -> memory / iterations-per-second series
- GET /train/histograms?sid=...     -> latest parameter histograms
- POST /remoteReceive               -> RemoteUIStatsStorageRouter sink
- GET /                             -> HTML overview (score chart via canvas)
- GET /model /system /histograms    -> HTML pages over the JSON endpoints
  (the TrainModule model/system/histogram tabs of deeplearning4j-play)
- POST /tsne/upload?sid=...         -> store 2-D embedding coords (+labels)
- GET /tsne/coords?sid=... /tsne    -> coords JSON / scatter page
  (the TsneModule of deeplearning4j-play, fed by plot.Tsne results)
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

from deeplearning4j_tpu.ui.stats import TYPE_ID
from deeplearning4j_tpu.ui.storage import InMemoryStatsStorage

_PAGE = """<!doctype html><html><head><title>dl4j-tpu training UI</title>
<style>body{font-family:sans-serif;margin:2em}canvas{border:1px solid #ccc}
table{border-collapse:collapse}td,th{border:1px solid #ddd;padding:4px 8px}
</style></head><body>
<p><a href="/">overview</a> | <a href="/model">model</a> |
<a href="/system">system</a> | <a href="/histograms">histograms</a></p>
<h2>Training overview</h2><div id="meta"></div>
<canvas id="score" width="800" height="300"></canvas>
<h3>Latest parameter norms</h3><table id="norms"></table>
<script>
async function refresh(){
 const sids=await (await fetch('/train/sessions')).json();
 if(!sids.length)return;
 const sid=sids[sids.length-1];
 const ov=await (await fetch('/train/overview?sid='+sid)).json();
 document.getElementById('meta').textContent=
   'session '+sid+' — '+ov.scores.length+' reports';
 const c=document.getElementById('score').getContext('2d');
 c.clearRect(0,0,800,300);
 const xs=ov.iterations, ys=ov.scores;
 if(xs.length>1){
  const ymax=Math.max(...ys), ymin=Math.min(...ys);
  c.beginPath();
  xs.forEach((x,i)=>{
   const px=40+(x-xs[0])/(xs[xs.length-1]-xs[0]||1)*740;
   const py=280-(ys[i]-ymin)/((ymax-ymin)||1)*260;
   i?c.lineTo(px,py):c.moveTo(px,py);});
  c.strokeStyle='#06c';c.stroke();
  c.fillText(ymax.toFixed(4),2,20);c.fillText(ymin.toFixed(4),2,285);
 }
 const t=document.getElementById('norms');
 t.innerHTML='<tr><th>param</th><th>L2 norm</th></tr>'+
  Object.entries(ov.latest_param_norms||{}).map(
   ([k,v])=>'<tr><td>'+k+'</td><td>'+v.toFixed(6)+'</td></tr>').join('');
}
refresh();setInterval(refresh,2000);
</script></body></html>"""

_NAV = ('<p><a href="/">overview</a> | <a href="/model">model</a> | '
        '<a href="/system">system</a> | <a href="/histograms">histograms</a>'
        ' | <a href="/tsne">tsne</a></p>')

_MODEL_PAGE = """<!doctype html><html><head><title>model</title>
<style>body{font-family:sans-serif;margin:2em}
table{border-collapse:collapse}td,th{border:1px solid #ddd;padding:4px 8px}
pre{background:#f6f6f6;padding:1em;max-width:60em;overflow:auto}
</style></head><body>""" + _NAV + """
<h2>Model</h2><table id="info"></table>
<h3>Configuration</h3><pre id="conf"></pre>
<script>
async function refresh(){
 const sids=await (await fetch('/train/sessions')).json();
 if(!sids.length)return;
 const m=await (await fetch('/train/model?sid='+sids[sids.length-1])).json();
 document.getElementById('info').innerHTML=
  Object.entries(m).filter(([k])=>k!='config_json').map(
   ([k,v])=>'<tr><th>'+k+'</th><td>'+JSON.stringify(v)+'</td></tr>').join('');
 try{document.getElementById('conf').textContent=
   JSON.stringify(JSON.parse(m.config_json||'{}'),null,2);}catch(e){}
}
refresh();
</script></body></html>"""

_SYSTEM_PAGE = """<!doctype html><html><head><title>system</title>
<style>body{font-family:sans-serif;margin:2em}canvas{border:1px solid #ccc}
</style></head><body>""" + _NAV + """
<h2>System</h2>
<h3>Host memory (RSS, MB)</h3><canvas id="mem" width="800" height="220"></canvas>
<h3>Iterations / second</h3><canvas id="ips" width="800" height="220"></canvas>
<h3>Training phases (ms per round)
<span style="color:#06c">host batch-prep</span> /
<span style="color:#c60">device round (incl. averaging)</span></h3>
<canvas id="phases" width="800" height="220"></canvas>
<script>
function line(id,xs,ys,color,clear=true,yminO=null,ymaxO=null){
 const c=document.getElementById(id).getContext('2d');
 if(clear)c.clearRect(0,0,800,220);
 const pairs=xs.map((x,i)=>[x,ys[i]]).filter(p=>p[1]!=null);
 if(pairs.length<2)return;
 const vy=pairs.map(p=>p[1]);
 const ymax=ymaxO!=null?ymaxO:Math.max(...vy);
 const ymin=yminO!=null?yminO:Math.min(...vy);
 const x0=pairs[0][0],x1=pairs[pairs.length-1][0];
 c.beginPath();
 pairs.forEach((p,i)=>{
  const px=40+(p[0]-x0)/((x1-x0)||1)*740;
  const py=200-(p[1]-ymin)/((ymax-ymin)||1)*180;
  i?c.lineTo(px,py):c.moveTo(px,py);});
 c.strokeStyle=color||'#06c';c.stroke();
 if(clear){c.fillText(ymax.toFixed(2),2,20);c.fillText(ymin.toFixed(2),2,205);}
}
async function refresh(){
 const sids=await (await fetch('/train/sessions')).json();
 if(!sids.length)return;
 const s=await (await fetch('/train/system?sid='+sids[sids.length-1])).json();
 line('mem',s.iterations,s.memory_mb);
 line('ips',s.iterations.slice(1),s.iterations_per_second.slice(1));
 // shared y-scale: the chart exists to COMPARE host prep vs device
 // round, so both series must map ms to pixels identically
 const pv=[...s.host_prep_ms,...s.device_round_ms].filter(v=>v!=null);
 if(pv.length){
  const pmin=Math.min(...pv),pmax=Math.max(...pv);
  line('phases',s.iterations,s.host_prep_ms,'#06c',true,pmin,pmax);
  line('phases',s.iterations,s.device_round_ms,'#c60',false,pmin,pmax);
 }
}
refresh();setInterval(refresh,2000);
</script></body></html>"""

_HISTOGRAM_PAGE = """<!doctype html><html><head><title>histograms</title>
<style>body{font-family:sans-serif;margin:2em}canvas{border:1px solid #ccc;
margin:4px}</style></head><body>""" + _NAV + """
<h2>Parameter histograms (latest report)</h2><div id="charts"></div>
<script>
async function refresh(){
 const sids=await (await fetch('/train/sessions')).json();
 if(!sids.length)return;
 const h=await (await fetch('/train/histograms?sid='+
                            sids[sids.length-1])).json();
 const root=document.getElementById('charts');root.innerHTML='';
 Object.entries(h.param_histograms||{}).forEach(([name,hist])=>{
  const div=document.createElement('div');
  div.innerHTML='<h4>'+name+' ['+hist.min.toFixed(4)+', '+
    hist.max.toFixed(4)+']</h4>';
  const cv=document.createElement('canvas');cv.width=420;cv.height=120;
  div.appendChild(cv);root.appendChild(div);
  const c=cv.getContext('2d');
  const n=hist.counts.length,m=Math.max(...hist.counts)||1;
  hist.counts.forEach((v,i)=>{
   c.fillStyle='#06c';
   c.fillRect(i*(420/n),120-v/m*110,(420/n)-1,v/m*110);});
 });
}
refresh();setInterval(refresh,3000);
</script></body></html>"""


_TSNE_PAGE = """<!doctype html><html><head><title>tsne</title>
<style>body{font-family:sans-serif;margin:2em}canvas{border:1px solid #ccc}
</style></head><body>""" + _NAV + """
<h2>t-SNE embedding</h2><div id="meta"></div>
<canvas id="plot" width="700" height="700"></canvas>
<script>
async function refresh(){
 const sids=await (await fetch('/tsne/sessions')).json();
 if(!sids.length)return;
 const sid=sids[sids.length-1];
 const d=await (await fetch('/tsne/coords?sid='+sid)).json();
 const pts=d.points||[];
 document.getElementById('meta').textContent=
   'session '+sid+' — '+pts.length+' points';
 if(!pts.length)return;
 const c=document.getElementById('plot').getContext('2d');
 c.clearRect(0,0,700,700);
 const xs=pts.map(p=>p[0]),ys=pts.map(p=>p[1]);
 const x0=Math.min(...xs),x1=Math.max(...xs),
       y0=Math.min(...ys),y1=Math.max(...ys);
 const colors=['#06c','#c60','#090','#909','#a00','#0aa','#660','#555'];
 const groups={};(d.labels||[]).forEach((l,i)=>{groups[l]=groups[l]??
   Object.keys(groups).length;});
 pts.forEach((p,i)=>{
  const px=20+(p[0]-x0)/((x1-x0)||1)*660;
  const py=680-(p[1]-y0)/((y1-y0)||1)*660;
  c.fillStyle=colors[(groups[(d.labels||[])[i]]||0)%colors.length];
  c.beginPath();c.arc(px,py,2.5,0,6.3);c.fill();
  if(pts.length<=200&&d.labels)c.fillText(d.labels[i],px+4,py);});
}
refresh();setInterval(refresh,5000);
</script></body></html>"""


class UIServer:
    """Singleton-ish server (reference: UIServer.getInstance())."""

    _instance = None

    @classmethod
    def get_instance(cls) -> "UIServer":
        if cls._instance is None:
            cls._instance = UIServer()
        return cls._instance

    def __init__(self, port: int = 0):
        self.storages: list = []
        self._remote_sink = InMemoryStatsStorage()
        self._tsne: dict = {}  # session id -> {"points": ..., "labels": ...}
        self._httpd = None
        self._thread = None
        self._port = port

    # ------------------------------------------------------------- lifecycle
    def attach(self, storage) -> None:
        if storage not in self.storages:
            self.storages.append(storage)

    def detach(self, storage) -> None:
        if storage in self.storages:
            self.storages.remove(storage)

    def enable_remote_listener(self) -> None:
        """Accept POSTed records on /remoteReceive (reference:
        RemoteReceiverModule)."""
        self.attach(self._remote_sink)

    @property
    def port(self) -> int:
        return self._httpd.server_address[1] if self._httpd else self._port

    def start(self) -> int:
        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def _json(self, obj, status=200):
                body = json.dumps(obj).encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _html(self, page: str):
                body = page.encode()
                self.send_response(200)
                self.send_header("Content-Type", "text/html")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                u = urlparse(self.path)
                q = parse_qs(u.query)
                sid = q.get("sid", [None])[0]
                pages = {"/": _PAGE, "/model": _MODEL_PAGE,
                         "/system": _SYSTEM_PAGE,
                         "/histograms": _HISTOGRAM_PAGE,
                         "/tsne": _TSNE_PAGE}
                if u.path in pages:
                    self._html(pages[u.path])
                elif u.path == "/train/sessions":
                    self._json(server.list_sessions())
                elif u.path == "/tsne/sessions":
                    self._json(sorted(server._tsne))
                elif u.path == "/tsne/coords":
                    self._json(server._tsne.get(sid, {}))
                elif u.path == "/train/overview":
                    self._json(server.overview(sid))
                elif u.path == "/train/model":
                    self._json(server.model_info(sid))
                elif u.path == "/train/system":
                    self._json(server.system_info(sid))
                elif u.path == "/train/histograms":
                    self._json(server.histograms(sid))
                else:
                    self._json({"error": "not found"}, 404)

            def do_POST(self):
                u = urlparse(self.path)
                if u.path == "/tsne/upload":
                    sid = parse_qs(u.query).get("sid", ["default"])[0]
                    try:
                        n = int(self.headers.get("Content-Length", 0))
                        msg = json.loads(self.rfile.read(n))
                        server.upload_tsne(sid, msg.get("points", []),
                                           msg.get("labels"))
                    except (ValueError, TypeError, IndexError,
                            KeyError) as e:
                        self._json({"error": f"bad payload: {e}"}, 400)
                        return
                    self._json({"status": "ok"})
                    return
                if u.path != "/remoteReceive":
                    self._json({"error": "not found"}, 404)
                    return
                n = int(self.headers.get("Content-Length", 0))
                msg = json.loads(self.rfile.read(n))
                sink = server._remote_sink
                {"static": sink.put_static_info,
                 "update": sink.put_update,
                 "meta": sink.put_storage_metadata}[msg["kind"]](
                     msg["record"])
                self._json({"status": "ok"})

        self._httpd = ThreadingHTTPServer(("127.0.0.1", self._port), Handler)
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self.port

    def stop(self) -> None:
        if self._httpd:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None

    # ----------------------------------------------------------------- views
    def list_sessions(self) -> list:
        out = []
        for s in self.storages:
            out.extend(s.list_session_ids())
        return sorted(set(out))

    def overview(self, session_id) -> dict:
        iters, scores, latest = [], [], None
        for s in self.storages:
            for r in s.get_all_updates_after(session_id, TYPE_ID):
                iters.append(r["data"].get("iteration"))
                scores.append(r["data"].get("score"))
                latest = r
        return {"iterations": iters, "scores": scores,
                "latest_param_norms":
                    latest["data"].get("param_norms") if latest else {},
                "latest_update_norms":
                    latest["data"].get("update_norms") if latest else {}}

    def model_info(self, session_id) -> dict:
        for s in self.storages:
            r = s.get_static_info(session_id, TYPE_ID)
            if r:
                return r["data"]
        return {}

    def system_info(self, session_id) -> dict:
        """Memory / throughput / phase-timing series (reference:
        TrainModule system tab + SparkTrainingStats' per-round
        data-fetch/fit timings)."""
        iters, mem, ips, prep, dev = [], [], [], [], []
        for s in self.storages:
            for r in s.get_all_updates_after(session_id, TYPE_ID):
                iters.append(r["data"].get("iteration"))
                mem.append(
                    (r["data"].get("memory_rss_bytes") or 0) / 1e6)
                ips.append(r["data"].get("iterations_per_second"))
                pt = r["data"].get("phase_timings") or {}
                prep.append(pt.get("host_prep_ms"))
                dev.append(pt.get("device_round_ms"))
        return {"iterations": iters, "memory_mb": mem,
                "iterations_per_second": ips,
                "host_prep_ms": prep, "device_round_ms": dev}

    # bounds for HTTP-uploaded embeddings: the UI port is reachable by any
    # local process, so memory growth must be capped (oldest session is
    # evicted, matching the rolling character of the stats storages)
    TSNE_MAX_POINTS = 200_000
    TSNE_MAX_SESSIONS = 32

    def upload_tsne(self, session_id, points, labels=None) -> None:
        """Store a 2-D embedding for the /tsne page (reference: TsneModule
        of deeplearning4j-play, which accepts uploaded coordinate files).
        ``points``: [N,2] array-like; ``labels``: optional length-N list.
        Typical source: ``plot.Tsne(...).fit(vectors)``."""
        if len(points) > self.TSNE_MAX_POINTS:
            raise ValueError(
                f"too many points ({len(points)} > {self.TSNE_MAX_POINTS})")
        if labels is not None and len(labels) != len(points):
            raise ValueError(
                f"labels length {len(labels)} != points length {len(points)}")
        pts = [[float(p[0]), float(p[1])] for p in points]
        # eviction below is least-recently-UPDATED: re-uploading an
        # existing session must refresh its position, or the actively
        # updated session gets evicted while stale ones survive
        self._tsne.pop(str(session_id), None)
        self._tsne[str(session_id)] = {
            "points": pts,
            "labels": [str(l) for l in labels] if labels is not None
            else None,
        }
        while len(self._tsne) > self.TSNE_MAX_SESSIONS:
            self._tsne.pop(next(iter(self._tsne)))

    def histograms(self, session_id) -> dict:
        """Latest collected parameter histograms (reference: TrainModule
        histogram tab; collected by StatsListener(collect_histograms=True)).
        'Latest' = max (timestamp, iteration) across ALL attached storages —
        attach order must not let a stale storage shadow a live one."""
        latest, latest_key = None, None
        for s in self.storages:
            for r in s.get_all_updates_after(session_id, TYPE_ID):
                if not r["data"].get("param_histograms"):
                    continue
                key = (r.get("timestamp", 0),
                       r["data"].get("iteration") or 0)
                if latest_key is None or key > latest_key:
                    latest, latest_key = r, key
        return {"iteration": latest["data"]["iteration"] if latest else None,
                "param_histograms":
                    latest["data"]["param_histograms"] if latest else {}}
