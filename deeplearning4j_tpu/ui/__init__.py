"""Observability/UI pipeline (reference: deeplearning4j-ui-parent).

listener -> storage -> web:

- ``StatsListener`` (stats.py) collects per-iteration score, parameter /
  update norms, timings, memory (reference:
  ui-model/.../stats/BaseStatsListener.java:44,297-381)
- ``StatsStorage`` API + InMemory/File impls (storage.py; reference:
  deeplearning4j-core api/storage/StatsStorage.java:30,
  ui-model InMemoryStatsStorage / FileStatsStorage.java:15). Wire format is
  JSON (replacing the reference's SBE codegen — no native codec needed).
- ``RemoteUIStatsStorageRouter`` posts updates over HTTP (remote.py;
  reference: api/storage/impl/RemoteUIStatsStorageRouter.java:33)
- ``UIServer`` (server.py) serves the stored stats as JSON + a static
  overview page (reference: deeplearning4j-play PlayUIServer.java:53 —
  stdlib http.server instead of the Play framework).
"""

from deeplearning4j_tpu.ui.stats import StatsListener, StatsReport
from deeplearning4j_tpu.ui.storage import (
    FileStatsStorage,
    InMemoryStatsStorage,
    StatsStorage,
    StatsStorageRouter,
)
from deeplearning4j_tpu.ui.remote import RemoteUIStatsStorageRouter
from deeplearning4j_tpu.ui.server import UIServer
from deeplearning4j_tpu.ui.components import (
    ChartHistogram,
    ChartHorizontalBar,
    ChartLine,
    ChartMatrix,
    ChartScatter,
    ChartStackedArea,
    Component,
    ComponentDiv,
    ComponentTable,
    ComponentText,
    render_html,
    render_html_file,
)
from deeplearning4j_tpu.ui.legacy import (
    ConvolutionalIterationListener,
    FlowIterationListener,
    HistogramIterationListener,
)

__all__ = [
    "StatsListener", "StatsReport", "StatsStorage", "StatsStorageRouter",
    "InMemoryStatsStorage", "FileStatsStorage", "RemoteUIStatsStorageRouter",
    "UIServer",
    "Component", "ComponentDiv", "ComponentTable", "ComponentText",
    "ChartLine", "ChartScatter", "ChartHistogram", "ChartHorizontalBar",
    "ChartStackedArea", "ChartMatrix", "render_html", "render_html_file",
    "HistogramIterationListener", "FlowIterationListener",
    "ConvolutionalIterationListener",
]
