"""Remote stats routing over HTTP.

Reference: api/storage/impl/RemoteUIStatsStorageRouter.java:33 — POSTs
serialized records to a UI server's /remoteReceive endpoint, with retry
backoff. Wire format here is JSON: {"kind": static|update|meta, "record":
{...}} — received by UIServer's RemoteReceiverModule equivalent.
"""

from __future__ import annotations

import json
import logging
import time
import urllib.request

log = logging.getLogger(__name__)


class RemoteUIStatsStorageRouter:
    def __init__(self, address: str, path: str = "/remoteReceive",
                 max_retries: int = 3, retry_backoff: float = 0.5,
                 timeout: float = 5.0):
        self.url = address.rstrip("/") + path
        self.max_retries = max_retries
        self.retry_backoff = retry_backoff
        self.timeout = timeout

    def _post(self, kind: str, record: dict) -> bool:
        body = json.dumps({"kind": kind, "record": record}).encode()
        req = urllib.request.Request(
            self.url, data=body, headers={"Content-Type": "application/json"},
            method="POST")
        for attempt in range(self.max_retries):
            try:
                with urllib.request.urlopen(req, timeout=self.timeout) as r:
                    return 200 <= r.status < 300
            except Exception as e:  # noqa: BLE001 — network path
                log.warning("remote stats post failed (%d/%d): %s",
                            attempt + 1, self.max_retries, e)
                time.sleep(self.retry_backoff * (2 ** attempt))
        return False

    def put_static_info(self, record: dict) -> None:
        self._post("static", record)

    def put_update(self, record: dict) -> None:
        self._post("update", record)

    def put_storage_metadata(self, record: dict) -> None:
        self._post("meta", record)
