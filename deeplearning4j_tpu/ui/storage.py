"""Stats storage API + implementations.

Reference: deeplearning4j-core api/storage/ (StatsStorageRouter — write side,
StatsStorage.java:30 — read/query side, Persistable), ui-model storage impls
(InMemoryStatsStorage.java:21, FileStatsStorage.java:15 — MapDB there, a
JSON-lines file here; no native storage engine required).

A record is a plain dict with routing keys session_id / type_id / worker_id /
timestamp plus a free-form ``data`` payload — the JSON-able stand-in for the
reference's SBE-encoded Persistable.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Callable, Optional


def make_record(session_id: str, type_id: str, worker_id: str, data: dict,
                timestamp: Optional[float] = None) -> dict:
    return {"session_id": session_id, "type_id": type_id,
            "worker_id": worker_id,
            "timestamp": time.time() if timestamp is None else timestamp,
            "data": data}


class StatsStorageRouter:
    """Write-side contract (reference: api/storage/StatsStorageRouter.java)."""

    def put_static_info(self, record: dict) -> None:
        raise NotImplementedError

    def put_update(self, record: dict) -> None:
        raise NotImplementedError

    def put_storage_metadata(self, record: dict) -> None:
        raise NotImplementedError


class StatsStorage(StatsStorageRouter):
    """Read/query side + listeners (reference: api/storage/StatsStorage.java:30
    — listSessionIDs, getAllUpdatesAfter, getStaticInfo, registerListener)."""

    def __init__(self):
        self._static: list = []
        self._updates: list = []
        self._meta: list = []
        self._listeners: list = []
        self._lock = threading.Lock()

    # ---- write side
    def put_static_info(self, record: dict) -> None:
        with self._lock:
            self._static.append(record)
        self._notify("static", record)

    def put_update(self, record: dict) -> None:
        with self._lock:
            self._updates.append(record)
        self._notify("update", record)

    def put_storage_metadata(self, record: dict) -> None:
        with self._lock:
            self._meta.append(record)
        self._notify("meta", record)

    def _notify(self, kind: str, record: dict) -> None:
        for cb in list(self._listeners):
            cb(kind, record)

    # ---- read side
    def list_session_ids(self) -> list:
        with self._lock:
            return sorted({r["session_id"]
                           for r in self._static + self._updates})

    def list_type_ids(self, session_id: str) -> list:
        with self._lock:
            return sorted({r["type_id"] for r in self._updates
                           if r["session_id"] == session_id})

    def list_worker_ids(self, session_id: str) -> list:
        with self._lock:
            return sorted({r["worker_id"] for r in self._updates
                           if r["session_id"] == session_id})

    def get_static_info(self, session_id: str, type_id: str,
                        worker_id: Optional[str] = None) -> Optional[dict]:
        with self._lock:
            for r in reversed(self._static):
                if (r["session_id"] == session_id
                        and r["type_id"] == type_id
                        and (worker_id is None
                             or r["worker_id"] == worker_id)):
                    return r
        return None

    def get_all_updates_after(self, session_id: str, type_id: str,
                              timestamp: float = 0.0) -> list:
        with self._lock:
            return [r for r in self._updates
                    if r["session_id"] == session_id
                    and r["type_id"] == type_id
                    and r["timestamp"] > timestamp]

    def get_latest_update(self, session_id: str, type_id: str
                          ) -> Optional[dict]:
        upd = self.get_all_updates_after(session_id, type_id)
        return upd[-1] if upd else None

    def num_updates(self) -> int:
        with self._lock:
            return len(self._updates)

    def register_stats_storage_listener(
            self, cb: Callable[[str, dict], None]) -> None:
        self._listeners.append(cb)

    def deregister_stats_storage_listener(self, cb) -> None:
        if cb in self._listeners:
            self._listeners.remove(cb)

    def close(self) -> None:
        pass


class InMemoryStatsStorage(StatsStorage):
    """reference: ui/storage/InMemoryStatsStorage.java:21 — StatsStorage's
    in-process lists ARE the store."""


class FileStatsStorage(StatsStorage):
    """JSON-lines file persistence (reference: ui/storage/FileStatsStorage.java
    :15, MapDB-backed there). Appends on write; reloads on open."""

    def __init__(self, path: str):
        super().__init__()
        self.path = path
        if os.path.exists(path):
            with open(path, encoding="utf-8") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    kind, record = json.loads(line)
                    {"static": self._static, "update": self._updates,
                     "meta": self._meta}[kind].append(record)
        self._f = open(path, "a", encoding="utf-8")

    def _append(self, kind: str, record: dict) -> None:
        self._f.write(json.dumps([kind, record]) + "\n")
        self._f.flush()

    def put_static_info(self, record: dict) -> None:
        super().put_static_info(record)
        self._append("static", record)

    def put_update(self, record: dict) -> None:
        super().put_update(record)
        self._append("update", record)

    def put_storage_metadata(self, record: dict) -> None:
        super().put_storage_metadata(record)
        self._append("meta", record)

    def close(self) -> None:
        self._f.close()
