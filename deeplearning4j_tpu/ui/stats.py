"""StatsListener: per-iteration training telemetry into a StatsStorageRouter.

Reference: ui-model/.../stats/BaseStatsListener.java:44 (collection loop
:297-381 — score, param/gradient/update norms + histograms, timings, memory,
GC). TPU adaptation: gradients never materialise outside the jitted step, so
update norms are computed from parameter deltas between reports (update =
param_t - param_{t-1}, identical to the reference's updates-by-difference
semantics for SGD-family updaters); JVM/GC memory becomes host RSS +
device-buffer byte counts from jax.

Histograms are computed on device (jnp.histogram) only at reporting
iterations, so steady-state training stays one XLA program per step.
"""

from __future__ import annotations

import os
import time

import jax
import numpy as np

from deeplearning4j_tpu.metrics.registry import global_registry
from deeplearning4j_tpu.optimize.listeners import TrainingListener
from deeplearning4j_tpu.ui.storage import StatsStorageRouter, make_record

TYPE_ID = "StatsListener"


class StatsReport:
    """Convenience view over a stored update record's data dict."""

    def __init__(self, record: dict):
        self.record = record
        self.data = record["data"]

    @property
    def score(self):
        return self.data["score"]

    @property
    def iteration(self):
        return self.data["iteration"]

    def param_norms(self):
        return self.data.get("param_norms", {})

    def update_norms(self):
        return self.data.get("update_norms", {})


def _flat_norms(params) -> dict:
    """{'layer/param': l2norm} over a 2-level pytree."""
    out = {}
    for lk, lp in params.items():
        for pk, v in lp.items():
            out[f"{lk}/{pk}"] = float(np.linalg.norm(np.asarray(v).ravel()))
    return out


def _rss_bytes() -> int:
    try:
        with open("/proc/self/statm") as f:
            return int(f.read().split()[1]) * os.sysconf("SC_PAGE_SIZE")
    except Exception:  # noqa: BLE001 — non-linux fallback
        return 0


class StatsListener(TrainingListener):
    def __init__(self, router: StatsStorageRouter, session_id: str = None,
                 worker_id: str = "worker_0", reporting_frequency: int = 10,
                 collect_histograms: bool = False, histogram_bins: int = 20,
                 registry=None):
        self.router = router
        self.session_id = session_id or f"session_{int(time.time())}"
        self.worker_id = worker_id
        self.frequency = max(1, reporting_frequency)
        self.collect_histograms = collect_histograms
        self.histogram_bins = histogram_bins
        self._static_sent = False
        self._last_params_norms = None
        self._last_time = None
        self._last_iter = None
        self._pending_phase_timings = None
        # training telemetry also lands in the shared registry (default:
        # process-global), so serving and training share one scrape
        self.metrics = registry if registry is not None \
            else global_registry()
        self._m_score = self.metrics.gauge(
            "training_score", "loss at the last reporting iteration",
            labels=("worker",))
        self._m_iteration = self.metrics.gauge(
            "training_iteration", "last reported iteration",
            labels=("worker",))
        self._m_ips = self.metrics.gauge(
            "training_iterations_per_second", "training throughput",
            labels=("worker",))
        self._m_rss = self.metrics.gauge(
            "training_memory_rss_bytes", "host RSS at report time",
            labels=("worker",))
        self._m_report_ms = self.metrics.histogram(
            "training_report_interval_ms",
            "wall time between reporting iterations", labels=("worker",))

    # ------------------------------------------------------------------ hooks
    def on_epoch_start(self, model):
        if not self._static_sent:
            self._send_static(model)

    def on_phase_timings(self, model, timings: dict):
        """Buffer the round's phase wall times; they ride on the next
        update record (reference: SparkTrainingStats routed through the
        stats-storage pipeline)."""
        self._pending_phase_timings = timings

    def _send_static(self, model):
        """Session/model/hardware info (reference: initializeReporting +
        StaticInfo :~250)."""
        conf = model.conf
        info = {
            "model_class": type(model).__name__,
            "num_params": int(model.num_params()) if model.params else 0,
            "num_layers": conf.n_layers() if hasattr(conf, "n_layers") else 0,
            "updater": type(conf.updater).__name__,
            "jax_backend": jax.default_backend(),
            "devices": [str(d) for d in jax.devices()],
            "config_json": conf.to_json(),
        }
        self.router.put_static_info(make_record(
            self.session_id, TYPE_ID, self.worker_id, info))
        self._static_sent = True

    def iteration_done(self, model, iteration: int):
        if not self._static_sent:
            self._send_static(model)
        if iteration % self.frequency != 0:
            return
        now = time.perf_counter()
        norms = _flat_norms(model.params)
        data = {
            "iteration": iteration,
            "epoch": getattr(model, "epoch", 0),
            "score": float(model.score_value),
            "param_norms": norms,
            "memory_rss_bytes": _rss_bytes(),
        }
        if self._last_params_norms is not None:
            # update magnitude proxy: |norm_t - norm_{t-1}| per param
            data["update_norms"] = {
                k: abs(norms[k] - self._last_params_norms[k])
                for k in norms if k in self._last_params_norms}
        if self._last_time is not None and iteration > self._last_iter:
            dt = now - self._last_time
            data["iterations_per_second"] = \
                (iteration - self._last_iter) / dt if dt > 0 else None
            data["duration_ms"] = dt * 1000.0
        if self._pending_phase_timings is not None:
            data["phase_timings"] = self._pending_phase_timings
            self._pending_phase_timings = None
        if self.collect_histograms:
            data["param_histograms"] = self._histograms(model.params)
        self.router.put_update(make_record(
            self.session_id, TYPE_ID, self.worker_id, data))
        self._m_score.labels(worker=self.worker_id).set(data["score"])
        self._m_iteration.labels(worker=self.worker_id).set(iteration)
        self._m_rss.labels(worker=self.worker_id).set(
            data["memory_rss_bytes"])
        if data.get("iterations_per_second"):
            self._m_ips.labels(worker=self.worker_id).set(
                data["iterations_per_second"])
        if "duration_ms" in data:
            self._m_report_ms.labels(worker=self.worker_id).observe(
                data["duration_ms"])
        self._last_params_norms = norms
        self._last_time = now
        self._last_iter = iteration

    def _histograms(self, params) -> dict:
        out = {}
        for lk, lp in params.items():
            for pk, v in lp.items():
                counts, edges = np.histogram(np.asarray(v).ravel(),
                                             bins=self.histogram_bins)
                out[f"{lk}/{pk}"] = {"counts": counts.tolist(),
                                     "min": float(edges[0]),
                                     "max": float(edges[-1])}
        return out
