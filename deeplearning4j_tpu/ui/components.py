"""Declarative UI components: charts/tables/text as data, rendered to a
standalone HTML page.

Reference: deeplearning4j-ui-components — Component.java type-tagged JSON
(ChartLine/ChartScatter/ChartHistogram/ChartHorizontalBar/ChartStackedArea,
ComponentTable/ComponentText/ComponentDiv, Style*) and
StaticPageUtil.renderHTML (freemarker template embedding the component
JSON + its JS renderers). Here: plain dataclasses with the same
``componentType`` tag discipline, and ``render_html`` emits one
self-contained page (inline canvas JS, zero external dependencies — the
reference pulls jquery/d3 from the classpath; offline TPU pods can't).
DecoratorAccordion and ChartTimeline are out of scope (stated, not
stubbed).
"""

from __future__ import annotations

import json
from dataclasses import asdict, dataclass, field
from typing import List, Optional

_REGISTRY = {}


def _register(cls):
    _REGISTRY[cls.__name__] = cls
    return cls


@dataclass
class Style:
    width: float = 640
    height: float = 300
    margin_top: float = 30
    margin_bottom: float = 30
    margin_left: float = 50
    margin_right: float = 20


@dataclass
class StyleChart(Style):
    stroke_width: float = 1.5
    point_size: float = 3.0
    series_colors: List[str] = field(default_factory=lambda: [
        "#0066cc", "#cc3300", "#009933", "#9933cc", "#ff9900"])
    axis_stroke_width: float = 1.0
    title_font_size: float = 14


@dataclass
class StyleTable(Style):
    header_color: str = "#dddddd"
    border_width: float = 1.0
    column_widths: Optional[List[float]] = None


@dataclass
class StyleText(Style):
    font: str = "sans-serif"
    font_size: float = 13.0
    color: str = "#000000"


class Component:
    """Base: serialization with the reference's componentType tag."""

    def to_dict(self) -> dict:
        d = {"componentType": type(self).__name__}
        d.update(asdict(self))
        return d

    def to_json(self) -> str:
        return json.dumps(self.to_dict())

    @staticmethod
    def from_json(s: str) -> "Component":
        d = json.loads(s)
        return Component.from_dict(d)

    @staticmethod
    def from_dict(d: dict) -> "Component":
        d = dict(d)
        t = d.pop("componentType", None)
        cls = _REGISTRY.get(t)
        if cls is None:
            raise ValueError(f"Unknown componentType '{t}'")
        return cls._from_dict(d)

    @classmethod
    def _from_dict(cls, d: dict) -> "Component":
        style = d.pop("style", None)
        obj = cls(**d)
        if isinstance(style, dict):
            obj.style = cls._style_cls()(**style)
        elif style is not None:
            obj.style = style
        return obj

    @classmethod
    def _style_cls(cls):
        return StyleChart


@_register
@dataclass
class ChartLine(Component):
    """Multi-series line chart (reference: ChartLine.java)."""

    title: str = ""
    x: List[List[float]] = field(default_factory=list)
    y: List[List[float]] = field(default_factory=list)
    series_names: List[str] = field(default_factory=list)
    style: StyleChart = field(default_factory=StyleChart)

    def add_series(self, name, xs, ys) -> "ChartLine":
        self.series_names.append(str(name))
        self.x.append([float(v) for v in xs])
        self.y.append([float(v) for v in ys])
        return self


@_register
@dataclass
class ChartScatter(Component):
    """Multi-series scatter (reference: ChartScatter.java)."""

    title: str = ""
    x: List[List[float]] = field(default_factory=list)
    y: List[List[float]] = field(default_factory=list)
    series_names: List[str] = field(default_factory=list)
    style: StyleChart = field(default_factory=StyleChart)

    def add_series(self, name, xs, ys) -> "ChartScatter":
        self.series_names.append(str(name))
        self.x.append([float(v) for v in xs])
        self.y.append([float(v) for v in ys])
        return self


@_register
@dataclass
class ChartHistogram(Component):
    """Variable-bin histogram (reference: ChartHistogram.java —
    lowerBounds/upperBounds/yValues)."""

    title: str = ""
    lower_bounds: List[float] = field(default_factory=list)
    upper_bounds: List[float] = field(default_factory=list)
    y_values: List[float] = field(default_factory=list)
    style: StyleChart = field(default_factory=StyleChart)

    def add_bin(self, lower, upper, y) -> "ChartHistogram":
        self.lower_bounds.append(float(lower))
        self.upper_bounds.append(float(upper))
        self.y_values.append(float(y))
        return self


@_register
@dataclass
class ChartHorizontalBar(Component):
    """Horizontal bars (reference: ChartHorizontalBar.java)."""

    title: str = ""
    labels: List[str] = field(default_factory=list)
    values: List[float] = field(default_factory=list)
    style: StyleChart = field(default_factory=StyleChart)


@_register
@dataclass
class ChartStackedArea(Component):
    """Stacked area chart (reference: ChartStackedArea.java): shared x,
    one y series per label, stacked cumulatively."""

    title: str = ""
    x: List[float] = field(default_factory=list)
    y: List[List[float]] = field(default_factory=list)
    labels: List[str] = field(default_factory=list)
    style: StyleChart = field(default_factory=StyleChart)


@_register
@dataclass
class ChartMatrix(Component):
    """Heatmap over a 2-D value grid (no direct reference analog as a
    component — the reference's ConvolutionalIterationListener renders
    activation grids server-side to PNG; here the grid is data and the
    page renders it, which also serves confusion matrices)."""

    title: str = ""
    values: List[List[float]] = field(default_factory=list)
    row_labels: List[str] = field(default_factory=list)
    col_labels: List[str] = field(default_factory=list)
    style: StyleChart = field(default_factory=StyleChart)


@_register
@dataclass
class ComponentTable(Component):
    """Simple table (reference: ComponentTable.java)."""

    header: List[str] = field(default_factory=list)
    content: List[List[str]] = field(default_factory=list)
    style: StyleTable = field(default_factory=StyleTable)

    @classmethod
    def _style_cls(cls):
        return StyleTable


@_register
@dataclass
class ComponentText(Component):
    """Styled text block (reference: ComponentText.java)."""

    text: str = ""
    style: StyleText = field(default_factory=StyleText)

    @classmethod
    def _style_cls(cls):
        return StyleText


@_register
@dataclass
class ComponentDiv(Component):
    """Container of child components (reference: ComponentDiv.java)."""

    children: List[dict] = field(default_factory=list)
    style: Style = field(default_factory=Style)

    @classmethod
    def _style_cls(cls):
        return Style

    def add(self, *components: Component) -> "ComponentDiv":
        self.children.extend(c.to_dict() for c in components)
        return self


_RENDER_JS = r"""
function renderComponent(c, root){
 function amin(a){let m=Infinity;for(let i=0;i<a.length;i++)if(a[i]<m)m=a[i];return m;}
 function amax(a){let m=-Infinity;for(let i=0;i<a.length;i++)if(a[i]>m)m=a[i];return m;}
 function flat(xs){const o=[];xs.forEach(s=>{for(let i=0;i<s.length;i++)o.push(s[i]);});return o;}
 const t=c.componentType;
 if(t==='ComponentDiv'){
  const div=document.createElement('div');root.appendChild(div);
  (c.children||[]).forEach(ch=>renderComponent(ch,div));return;}
 if(t==='ComponentText'){
  const p=document.createElement('p');p.textContent=c.text;
  p.style.font=c.style.font_size+'px '+c.style.font;
  p.style.color=c.style.color;root.appendChild(p);return;}
 if(t==='ComponentTable'){
  const tb=document.createElement('table');tb.style.borderCollapse='collapse';
  const tr=document.createElement('tr');
  (c.header||[]).forEach(h=>{const th=document.createElement('th');
   th.textContent=h;th.style.background=c.style.header_color;
   th.style.border='1px solid #999';th.style.padding='3px 8px';
   tr.appendChild(th);});
  tb.appendChild(tr);
  (c.content||[]).forEach(row=>{const r=document.createElement('tr');
   row.forEach(v=>{const td=document.createElement('td');td.textContent=v;
    td.style.border='1px solid #ccc';td.style.padding='3px 8px';
    r.appendChild(td);});tb.appendChild(r);});
  root.appendChild(tb);return;}
 // charts share a canvas + axes
 const st=c.style,W=st.width,H=st.height;
 const l=st.margin_left,r=st.margin_right,tp=st.margin_top,b=st.margin_bottom;
 const h=document.createElement('h4');h.textContent=c.title||'';
 root.appendChild(h);
 const cv=document.createElement('canvas');cv.width=W;cv.height=H;
 cv.style.border='1px solid #ccc';root.appendChild(cv);
 const g=cv.getContext('2d');
 const pw=W-l-r,ph=H-tp-b;
 function axes(x0,x1,y0,y1){
  g.strokeStyle='#333';g.beginPath();g.moveTo(l,tp);g.lineTo(l,tp+ph);
  g.lineTo(l+pw,tp+ph);g.stroke();
  g.fillStyle='#333';
  g.fillText(y1.toPrecision(3),2,tp+8);g.fillText(y0.toPrecision(3),2,tp+ph);
  g.fillText(x0.toPrecision(3),l,H-4);g.fillText(x1.toPrecision(3),l+pw-30,H-4);}
 function px(v,x0,x1){return l+(v-x0)/((x1-x0)||1)*pw;}
 function py(v,y0,y1){return tp+ph-(v-y0)/((y1-y0)||1)*ph;}
 if(t==='ChartLine'||t==='ChartScatter'){
  const xs=flat(c.x),ys=flat(c.y);
  if(!xs.length)return;
  const x0=amin(xs),x1=amax(xs);
  const y0=amin(ys),y1=amax(ys);
  axes(x0,x1,y0,y1);
  c.x.forEach((sx,i)=>{
   const col=st.series_colors[i%st.series_colors.length];
   if(t==='ChartLine'){
    g.strokeStyle=col;g.lineWidth=st.stroke_width;g.beginPath();
    sx.forEach((v,j)=>{const X=px(v,x0,x1),Y=py(c.y[i][j],y0,y1);
     j?g.lineTo(X,Y):g.moveTo(X,Y);});
    g.stroke();
   }else{
    g.fillStyle=col;
    sx.forEach((v,j)=>{g.beginPath();
     g.arc(px(v,x0,x1),py(c.y[i][j],y0,y1),st.point_size,0,6.283);
     g.fill();});}
   g.fillStyle=col;
   g.fillText(c.series_names[i]||('s'+i),l+pw-80,tp+12+12*i);});
 }else if(t==='ChartHistogram'){
  if(!c.y_values.length)return;
  const x0=amin(c.lower_bounds),x1=amax(c.upper_bounds);
  const y1=amax(c.y_values);
  axes(x0,x1,0,y1);
  g.fillStyle=st.series_colors[0];
  c.y_values.forEach((v,i)=>{
   const X0=px(c.lower_bounds[i],x0,x1),X1=px(c.upper_bounds[i],x0,x1);
   g.fillRect(X0,py(v,0,y1),Math.max(X1-X0-1,1),tp+ph-py(v,0,y1));});
 }else if(t==='ChartHorizontalBar'){
  if(!c.values.length)return;
  const v1=Math.max(amax(c.values),0);
  const bh=ph/c.values.length;
  c.values.forEach((v,i)=>{
   g.fillStyle=st.series_colors[i%st.series_colors.length];
   g.fillRect(l,tp+i*bh+2,(v/(v1||1))*pw,bh-4);
   g.fillStyle='#333';g.fillText(c.labels[i]||'',2,tp+i*bh+bh/2);});
 }else if(t==='ChartStackedArea'){
  if(!c.x.length)return;
  const x0=amin(c.x),x1=amax(c.x);
  const sums=c.x.map((_,j)=>c.y.reduce((a,s)=>a+s[j],0));
  const y1=amax(sums);
  axes(x0,x1,0,y1);
  let base=c.x.map(()=>0);
  c.y.forEach((s,i)=>{
   const top=base.map((bv,j)=>bv+s[j]);
   g.fillStyle=st.series_colors[i%st.series_colors.length];
   g.beginPath();
   c.x.forEach((v,j)=>{const X=px(v,x0,x1),Y=py(top[j],0,y1);
    j?g.lineTo(X,Y):g.moveTo(X,Y);});
   for(let j=c.x.length-1;j>=0;j--)
    g.lineTo(px(c.x[j],x0,x1),py(base[j],0,y1));
   g.closePath();g.fill();
   g.fillStyle='#333';g.fillText(c.labels[i]||('s'+i),l+pw-80,tp+12+12*i);
   base=top;});
 }else if(t==='ChartMatrix'){
  const R=c.values.length;if(!R)return;
  const C=c.values[0].length;
  const vmin=amin(c.values.map(amin)),vmax=amax(c.values.map(amax));
  const cw=pw/C,chh=ph/R;
  for(let i=0;i<R;i++)for(let j=0;j<C;j++){
   const u=(c.values[i][j]-vmin)/((vmax-vmin)||1);
   const hue=240-240*u;  // blue (low) -> red (high)
   g.fillStyle='hsl('+hue+',80%,'+(30+40*u)+'%)';
   g.fillRect(l+j*cw,tp+i*chh,Math.ceil(cw),Math.ceil(chh));}
  g.fillStyle='#333';
  (c.row_labels||[]).forEach((s,i)=>g.fillText(s,2,tp+i*chh+chh/2));
  (c.col_labels||[]).forEach((s,j)=>g.fillText(s,l+j*cw,H-4));
  g.fillText(vmax.toPrecision(3)+' max',l+pw-70,tp+10);
  g.fillText(vmin.toPrecision(3)+' min',l+pw-70,tp+22);
 }
}
"""

_PAGE_TEMPLATE = """<!doctype html><html><head><meta charset="utf-8">
<title>{title}</title>
<style>body{{font-family:sans-serif;margin:2em}}</style></head><body>
<div id="root"></div>
<script>
const COMPONENTS = {data};
{render_js}
const root = document.getElementById('root');
COMPONENTS.forEach(c => renderComponent(c, root));
</script></body></html>"""


def render_html(components, title: str = "dl4j-tpu report") -> str:
    """Render components to ONE self-contained HTML page — data and
    renderer embedded (reference: StaticPageUtil.renderHTML)."""
    import html
    data = json.dumps([c.to_dict() if isinstance(c, Component) else c
                       for c in components])
    # '</script>' (or any '</') inside a string value would terminate the
    # script element mid-JSON and let component text inject markup;
    # '<\/' is identical to '</' to the JS parser but inert to the HTML one
    data = data.replace("</", "<\\/")
    return _PAGE_TEMPLATE.format(title=html.escape(title), data=data,
                                 render_js=_RENDER_JS)


def render_html_file(components, path: str,
                     title: str = "dl4j-tpu report") -> None:
    """render_html to a file (reference: StaticPageUtil.saveHTMLFile)."""
    with open(path, "w", encoding="utf-8") as f:
        f.write(render_html(components, title))
