"""tpu-dl4j: a TPU-native deep-learning framework with DeepLearning4j's capabilities.

A ground-up JAX/XLA/Pallas re-design of the DL4J framework layer (reference:
dawncc/deeplearning4j). Where DL4J hand-writes per-layer forward/backward over ND4J
kernels, this framework expresses layers as pure functions over pytrees, differentiates
with `jax.grad`, compiles whole training steps with `jax.jit`, and scales out with a
single sharded step over a `jax.sharding.Mesh` (replacing ParallelWrapper thread
averaging, Spark parameter averaging, and the Aeron parameter server).

Package map (mirrors the reference's module inventory, SURVEY.md section 2):

- ``ops``       -- tensor op facade (activations, losses, conv, rng) over jax.numpy/lax
- ``nn``        -- config system, layers, MultiLayerNetwork, ComputationGraph, updaters
- ``optimize``  -- listeners
- ``evaluation`` -- Evaluation / RegressionEvaluation / ROC
- ``datasets``  -- DataSet / iterators / built-in datasets
- ``utils``     -- serialization (ModelSerializer-style zips), pytree helpers
"""

__version__ = "0.1.0"


def enable_compile_cache(directory: str,
                         min_compile_secs: float = 0.5) -> None:
    """Enable jax's persistent XLA compilation cache.

    Through a remote-compile TPU backend a cold ResNet-class compile costs
    tens of seconds per process; with the cache a second process reuses
    the serialized executable (measured 13.7 s -> 2.4 s cold-to-first-
    output for LeNet). Also honored automatically at import when the
    ``DL4J_TPU_COMPILE_CACHE`` env var names a directory."""
    import jax

    jax.config.update("jax_compilation_cache_dir", directory)
    jax.config.update("jax_persistent_cache_min_compile_time_secs",
                      min_compile_secs)


def _maybe_enable_cache_from_env() -> None:
    import os

    directory = os.environ.get("DL4J_TPU_COMPILE_CACHE")
    if directory:
        enable_compile_cache(directory)


_maybe_enable_cache_from_env()
