"""tpu-dl4j: a TPU-native deep-learning framework with DeepLearning4j's capabilities.

A ground-up JAX/XLA/Pallas re-design of the DL4J framework layer (reference:
dawncc/deeplearning4j). Where DL4J hand-writes per-layer forward/backward over ND4J
kernels, this framework expresses layers as pure functions over pytrees, differentiates
with `jax.grad`, compiles whole training steps with `jax.jit`, and scales out with a
single sharded step over a `jax.sharding.Mesh` (replacing ParallelWrapper thread
averaging, Spark parameter averaging, and the Aeron parameter server).

Package map (mirrors the reference's module inventory, SURVEY.md section 2):

- ``ops``       -- tensor op facade (activations, losses, conv, rng) over jax.numpy/lax
- ``nn``        -- config system, layers, MultiLayerNetwork, ComputationGraph, updaters
- ``optimize``  -- listeners
- ``evaluation`` -- Evaluation / RegressionEvaluation / ROC
- ``datasets``  -- DataSet / iterators / built-in datasets
- ``utils``     -- serialization (ModelSerializer-style zips), pytree helpers
"""

__version__ = "0.1.0"
